"""Push-sum kernel vs a pure-NumPy oracle, plus the §4 invariants: per-round
mass conservation, convergence to the true mean (pop-1)/2, receipt-gated
termination counters, and determinism under a seed."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
from cop5615_gossip_protocol_tpu.models import pushsum as P
from cop5615_gossip_protocol_tpu.models.runner import make_round_fn


def np_round(s, w, term, conv, targets, send_ok, delta, term_rounds):
    """10-line NumPy oracle for one synchronous push-sum round."""
    s_send = np.where(send_ok, s / 2, 0.0)
    w_send = np.where(send_ok, w / 2, 0.0)
    inbox_s = np.zeros_like(s)
    inbox_w = np.zeros_like(w)
    np.add.at(inbox_s, targets, s_send)
    np.add.at(inbox_w, targets, w_send)
    s_new = (s - s_send) + inbox_s
    w_new = (w - w_send) + inbox_w
    received = inbox_w > 0
    stable = np.abs(s_new / w_new - s / w) <= delta
    term_new = np.where(received, np.where(stable, term + 1, 0), term)
    conv_new = conv | (term_new >= term_rounds)
    return s_new, w_new, term_new, conv_new


def test_round_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    n = 33
    s = rng.uniform(0, n, n)
    w = rng.uniform(0.5, 2.0, n)
    term = rng.integers(0, 3, n).astype(np.int32)
    conv = rng.random(n) < 0.2
    targets = rng.integers(0, n, n).astype(np.int32)
    send_ok = rng.random(n) < 0.9

    state = P.PushSumState(jnp.asarray(s), jnp.asarray(w), jnp.asarray(term), jnp.asarray(conv))
    out = P.round_from_targets(state, jnp.asarray(targets), jnp.asarray(send_ok), n, 1e-10, 3)
    es, ew, et, ec = np_round(s, w, term, conv, targets, send_ok, 1e-10, 3)
    np.testing.assert_allclose(np.asarray(out.s), es, rtol=1e-12)
    np.testing.assert_allclose(np.asarray(out.w), ew, rtol=1e-12)
    np.testing.assert_array_equal(np.asarray(out.term), et)
    np.testing.assert_array_equal(np.asarray(out.conv), ec)


@pytest.mark.parametrize("kind", ["full", "grid2d", "imp3d", "line", "torus3d"])
def test_mass_conservation(kind):
    # Σs and Σw are invariant under every round (the reference preserves this
    # too — converged nodes relay mass untouched, Q5/program.fs:125-127).
    topo = build_topology(kind, 64, seed=0)
    cfg = SimConfig(n=64, topology=kind, algorithm="push-sum", dtype="float64")
    key = jax.random.PRNGKey(0)
    round_fn, state, key_data, targs = make_round_fn(topo, cfg, key)
    total_s0 = float(jnp.sum(state.s))
    total_w0 = float(jnp.sum(state.w))
    for rnd in range(50):
        state = round_fn(state, jnp.int32(rnd), key_data, *targs)
        assert float(jnp.sum(state.s)) == pytest.approx(total_s0, rel=1e-12)
        assert float(jnp.sum(state.w)) == pytest.approx(total_w0, rel=1e-12)


@pytest.mark.parametrize("kind", ["full", "grid2d", "imp3d", "imp2d", "torus3d"])
def test_converges_to_true_mean(kind):
    cfg = SimConfig(
        n=256, topology=kind, algorithm="push-sum", dtype="float64",
        max_rounds=100_000, chunk_rounds=2048,
    )
    topo = build_topology(kind, 256, seed=0)
    r = run(topo, cfg)
    assert r.converged, f"did not converge in {r.rounds} rounds"
    assert r.estimate_mae < 1e-6 * topo.n


def test_receipt_gating():
    # A node that receives nothing must not advance its termination counter —
    # in the reference, no message means the handler never runs (SURVEY.md
    # §3.3). Node 2 is isolated: send_ok False and nobody targets it.
    s = jnp.asarray([1.0, 2.0, 3.0])
    w = jnp.ones(3)
    term = jnp.zeros(3, jnp.int32)
    conv = jnp.zeros(3, bool)
    state = P.PushSumState(s, w, term, conv)
    targets = jnp.asarray([1, 0, 0], jnp.int32)
    send_ok = jnp.asarray([True, True, False])
    out = P.round_from_targets(state, targets, send_ok, 3, 1e-10, 3)
    assert int(out.term[2]) == 0
    # its ratio is untouched, so a huge delta would otherwise mark it stable
    out_loose = P.round_from_targets(state, targets, send_ok, 3, 1e6, 3)
    assert int(out_loose.term[2]) == 0  # still gated
    assert int(out_loose.term[0]) == 1  # receivers do advance under loose delta


def test_term_resets_on_ratio_jump():
    # Ratio-changing receipt resets the streak (program.fs:130-131).
    state = P.PushSumState(
        jnp.asarray([0.0, 100.0]), jnp.ones(2), jnp.asarray([2, 2], jnp.int32),
        jnp.zeros(2, bool),
    )
    targets = jnp.asarray([1, 0], jnp.int32)
    out = P.round_from_targets(state, targets, jnp.asarray([True, True]), 2, 1e-10, 3)
    assert int(out.term[0]) == 0 and int(out.term[1]) == 0


def test_initial_term_round_quirk_q4():
    cfg_ref = SimConfig(n=8, semantics="reference", algorithm="push-sum")
    cfg_hon = SimConfig(n=8, algorithm="push-sum")
    assert cfg_ref.initial_term_round == 1  # program.fs:79
    assert cfg_hon.initial_term_round == 0


def test_determinism():
    cfg = SimConfig(n=128, topology="full", algorithm="push-sum", dtype="float64")
    topo = build_topology("full", 128)
    r1 = run(topo, cfg)
    r2 = run(topo, cfg)
    assert r1.rounds == r2.rounds
    assert r1.estimate_mae == r2.estimate_mae


def test_float32_policy():
    # delta=1e-10 is unreachable in f32; the resolved default must rescale.
    cfg = SimConfig(n=64, topology="full", algorithm="push-sum", dtype="float32")
    assert cfg.resolved_delta == 1e-6
    topo = build_topology("full", 64)
    r = run(topo, cfg)
    assert r.converged
    assert r.estimate_mae < 1.0


def test_fault_injection_still_converges():
    cfg = SimConfig(
        n=64, topology="full", algorithm="push-sum", dtype="float64",
        fault_rate=0.3, max_rounds=50_000,
    )
    topo = build_topology("full", 64)
    r = run(topo, cfg)
    assert r.converged


def test_global_termination_stops_on_residual():
    # VERDICT r3 #7: --termination global stops when every node's per-round
    # RELATIVE ratio change is <= delta, instead of the per-node latch.
    import pytest
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.models.runner import run

    n = 4096
    topo = build_topology("torus3d", n)
    r_l = run(topo, SimConfig(n=n, topology="torus3d", algorithm="push-sum",
                              max_rounds=200000))
    r_g = run(topo, SimConfig(n=n, topology="torus3d", algorithm="push-sum",
                              termination="global", max_rounds=200000))
    assert r_g.converged and r_g.converged_count == n
    # Stops no later than the local latch's straggler tail and delivers
    # comparable estimate quality (relative to the mean (n-1)/2).
    assert r_g.rounds <= r_l.rounds
    assert r_g.estimate_mae / ((n - 1) / 2) < 1e-5
    # All-or-nothing: conv is a global flag, so partial convergence counts
    # can never appear.
    assert r_g.converged_count in (0, n)


def test_global_termination_gating():
    import pytest
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.models.runner import run

    with pytest.raises(ValueError, match="push-sum"):
        SimConfig(n=64, topology="line", algorithm="gossip",
                  termination="global")
    with pytest.raises(ValueError, match="reference"):
        SimConfig(n=64, topology="line", algorithm="push-sum",
                  semantics="reference", termination="global")
    # Single-device fused + global is supported in-kernel since VERDICT r3
    # #5 (tests/test_fused_global.py); the sharded compositions run it too
    # since VERDICT r4 #8 (tests/test_fused_sharded.py,
    # tests/test_fused_hbm_sharded.py) — but a layout with no exact plan
    # must still raise with BOTH tier reasons, not silently fall back.
    cfg = SimConfig(n=512, topology="torus3d", algorithm="push-sum",
                    termination="global", engine="fused", n_devices=3)
    with pytest.raises(ValueError, match="HBM-streaming composition"):
        run(build_topology("torus3d", 512), cfg)


def test_global_termination_sharded_composes():
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.models.runner import run
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh
    from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded

    n = 4096
    topo = build_topology("torus3d", n)
    cfg = SimConfig(n=n, topology="torus3d", algorithm="push-sum",
                    termination="global", max_rounds=200000)
    r1 = run(topo, cfg)
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert r8.converged
    # Halo delivery preserves accumulation order; the global residual flag
    # composes across shards into the same stop round.
    assert r8.rounds == r1.rounds


def test_global_termination_sharded_pad_exact_count():
    # ADVICE r3: with n not a device multiple, the global-latch broadcast
    # must not mark pad slots converged — converged_count is exactly n (not
    # n_pad) and the estimate gate sees only real nodes.
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh
    from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded

    n = 1001  # n_pad = 1008 on 8 devices: 7 pad lanes
    topo = build_topology("full", n)
    cfg = SimConfig(n=n, topology="full", algorithm="push-sum",
                    termination="global", max_rounds=200000)
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert r8.converged
    assert r8.converged_count == n
    assert r8.estimate_mae / ((n - 1) / 2) < 1e-4
