"""Speculative chunk pipelining (models/pipeline.py): bitwise parity of
pipelined vs serial chunk loops on every engine class, exact rounds
accounting, and the overshoot no-op contract.

The pipelined driver dispatches chunk k+1 before reading chunk k's
termination predicate; correctness rests on two properties these tests pin
per engine:

- a chunk dispatched at an already-terminal carry is a bitwise NO-OP on
  protocol state and the round counter (so speculative overshoot past
  convergence changes nothing, and reported ``rounds`` stays exact);
- chunk-boundary side effects (hooks, the stall watchdog) observe the same
  boundaries with the same states as the serial loop, in order.
"""

import jax
import numpy as np
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models import pipeline as pipeline_mod
from cop5615_gossip_protocol_tpu.models.runner import run


def _run_capture(kind, n, depth, hooks=True, **cfg_kwargs):
    """Run one config at the given pipeline depth, capturing every chunk
    boundary's (rounds, state-as-numpy)."""
    cfg = SimConfig(n=n, topology=kind, pipeline_chunks=depth, **cfg_kwargs)
    topo = build_topology(kind, n, seed=cfg.seed)
    boundaries = []

    def hook(rounds, state):
        boundaries.append((rounds, jax.tree.map(np.asarray, state)))

    result = run(topo, cfg, on_chunk=hook if hooks else None)
    return result, boundaries


def _assert_identical(res_a, bounds_a, res_b, bounds_b):
    assert res_a.rounds == res_b.rounds
    assert res_a.converged_count == res_b.converged_count
    assert res_a.converged == res_b.converged
    assert res_a.outcome == res_b.outcome
    assert [r for r, _ in bounds_a] == [r for r, _ in bounds_b]
    for (_, sa), (_, sb) in zip(bounds_a, bounds_b):
        for f in sa._fields:
            np.testing.assert_array_equal(
                getattr(sa, f), getattr(sb, f), err_msg=f
            )


# ------------------------------------------------------- per-engine parity


@pytest.mark.parametrize("depth", [2, 4])
def test_chunked_scatter_parity_mid_chunk_convergence(depth):
    # chunk_rounds=7 does not divide the convergence round: the final chunk
    # early-exits mid-chunk, and the speculative in-flight chunk must be a
    # no-op (rounds stays exact, not rounded up to a chunk boundary).
    serial = _run_capture("full", 64, 1, algorithm="gossip", seed=3,
                          chunk_rounds=7, delivery="scatter")
    piped = _run_capture("full", 64, depth, algorithm="gossip", seed=3,
                         chunk_rounds=7, delivery="scatter")
    _assert_identical(*serial, *piped)
    assert serial[0].outcome == "converged"
    assert serial[0].rounds % 7 != 0  # genuinely mid-chunk


def test_chunked_stencil_pushsum_parity():
    serial = _run_capture("line", 48, 1, algorithm="push-sum", seed=0,
                          chunk_rounds=512, delivery="stencil")
    piped = _run_capture("line", 48, 3, algorithm="push-sum", seed=0,
                         chunk_rounds=512, delivery="stencil")
    _assert_identical(*serial, *piped)
    assert serial[0].converged


def test_chunked_pool_parity():
    serial = _run_capture("full", 64, 1, algorithm="push-sum", seed=1,
                          chunk_rounds=16, delivery="pool")
    piped = _run_capture("full", 64, 2, algorithm="push-sum", seed=1,
                         chunk_rounds=16, delivery="pool")
    _assert_identical(*serial, *piped)


def test_chunked_crash_schedule_parity():
    # Faulted run (crash-stop schedule + quorum): the termination predicate
    # is the quorum over live nodes; pipelined boundaries must replay it
    # bitwise, including the frozen dead nodes' state.
    kwargs = dict(algorithm="gossip", seed=2, chunk_rounds=8,
                  crash_schedule="3:8,6:4", quorum=0.9, max_rounds=4000)
    serial = _run_capture("full", 64, 1, **kwargs)
    piped = _run_capture("full", 64, 3, **kwargs)
    _assert_identical(*serial, *piped)
    assert serial[0].outcome == "converged"


def test_chunked_delay_dup_parity():
    # Delay ring + duplicate delivery: the carry is (state, ring) — the
    # pipeline must thread the compound carry unchanged.
    kwargs = dict(algorithm="push-sum", seed=0, chunk_rounds=64,
                  delay_rounds=2, dup_rate=0.05, delivery="scatter")
    serial = _run_capture("full", 48, 1, **kwargs)
    piped = _run_capture("full", 48, 2, **kwargs)
    _assert_identical(*serial, *piped)


def test_stalled_watchdog_parity_discards_speculation():
    # A stalled run (the reference's line-topology hang as a measured
    # outcome): the watchdog fires at a retired boundary while speculative
    # chunks are in flight — those must be DISCARDED, leaving outcome,
    # rounds, and final state bitwise the serial loop's.
    kwargs = dict(algorithm="gossip", seed=0, engine="chunked",
                  fault_rate=0.9999, stall_chunks=3, chunk_rounds=16,
                  max_rounds=5000)
    serial = _run_capture("line", 60, 1, **kwargs)
    piped = _run_capture("line", 60, 4, **kwargs)
    _assert_identical(*serial, *piped)
    assert serial[0].outcome == "stalled"
    assert serial[0].rounds < 5000


def test_sharded_parity():
    serial = _run_capture("full", 64, 1, algorithm="gossip", seed=3,
                          chunk_rounds=7, n_devices=8)
    piped = _run_capture("full", 64, 2, algorithm="gossip", seed=3,
                         chunk_rounds=7, n_devices=8)
    _assert_identical(*serial, *piped)
    assert serial[0].converged


def test_fused_interpret_parity():
    # The fused Pallas engine (interpret mode off-TPU): parity of the
    # threaded (rnd, done) carry against the serial loop at a bounded
    # round budget (full convergence on a ring is interpret-mode slow).
    kwargs = dict(algorithm="gossip", seed=0, engine="fused",
                  chunk_rounds=8, max_rounds=24)
    serial = _run_capture("ring", 256, 1, **kwargs)
    piped = _run_capture("ring", 256, 3, **kwargs)
    _assert_identical(*serial, *piped)
    assert serial[0].rounds == 24


# ------------------------------------------------- overshoot no-op contract


def test_overshoot_chunk_is_noop_on_resume():
    # Run to convergence, then resume AT the converged state with a deep
    # pipeline: every dispatched chunk is past termination, so the run must
    # retire with zero additional rounds and a bitwise-unchanged state.
    res, bounds = _run_capture("full", 64, 2, algorithm="gossip", seed=3,
                               chunk_rounds=7)
    assert res.outcome == "converged"
    final_rounds, final_state = bounds[-1]
    assert final_rounds == res.rounds

    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                    chunk_rounds=7, pipeline_chunks=4)
    topo = build_topology("full", 64, seed=3)
    import cop5615_gossip_protocol_tpu.models.gossip as gossip_mod

    start = gossip_mod.GossipState(*(jax.numpy.asarray(x)
                                     for x in final_state))
    boundaries = []

    def hook(rounds, state):
        boundaries.append((rounds, jax.tree.map(np.asarray, state)))

    res2 = run(topo, cfg, on_chunk=hook, start_state=start,
               start_round=final_rounds)
    assert res2.rounds == final_rounds  # exact: no phantom rounds
    assert res2.outcome == "converged"
    for rounds, state in boundaries:
        assert rounds == final_rounds
        for f in state._fields:
            np.testing.assert_array_equal(
                getattr(state, f), getattr(final_state, f), err_msg=f
            )


def test_donating_path_matches_hooked_path():
    # No hooks -> donation + speculation; hooks -> buffered path. Same
    # trajectory either way (donation aliases buffers, never values).
    cfg_kwargs = dict(algorithm="push-sum", seed=1, chunk_rounds=32,
                      delivery="pool")
    hooked, _ = _run_capture("full", 64, 2, hooks=True, **cfg_kwargs)
    donating, _ = _run_capture("full", 64, 2, hooks=False, **cfg_kwargs)
    assert donating.rounds == hooked.rounds
    assert donating.converged_count == hooked.converged_count
    assert donating.estimate_mae == hooked.estimate_mae


# ------------------------------------------------------- driver unit tests


def _fake_dispatch(log, fail_after=None):
    """Host-side model of a conforming chunk fn: advances rnd to round_end
    unless a 'convergence' round is crossed; no-op once done."""

    def dispatch(state, rnd, done, round_end):
        log.append(("dispatch", int(rnd), int(round_end)))
        if done:
            return state, rnd, done
        conv_at = state["conv_at"]
        new_rnd = min(round_end, conv_at) if conv_at is not None else round_end
        return state, new_rnd, conv_at is not None and new_rnd >= conv_at

    return dispatch


def test_driver_exact_rounds_and_retire_order():
    log, retired = [], []
    result = pipeline_mod.run_chunks(
        dispatch=_fake_dispatch(log),
        state0={"conv_at": 23}, rnd0=0, done0=False,
        start_round=0, max_rounds=1000, stride=10, depth=3,
        on_retire=lambda r, s: retired.append(r),
    )
    assert result.rounds == 23  # exact, not rounded to a chunk boundary
    assert result.done
    assert retired == [10, 20, 23]  # serial boundary sequence, in order


def test_driver_watchdog_discards_inflight():
    log = []
    stops = iter([False, True])
    result = pipeline_mod.run_chunks(
        dispatch=_fake_dispatch(log),
        state0={"conv_at": None}, rnd0=0, done0=False,
        start_round=0, max_rounds=1000, stride=10, depth=4,
        should_stop=lambda r, s: next(stops),
    )
    assert result.rounds == 20  # stopped at the second retired boundary
    assert not result.done
    assert result.chunks_speculative > 0  # in-flight work was discarded


def test_driver_donate_rejects_hooks():
    with pytest.raises(ValueError, match="donation"):
        pipeline_mod.run_chunks(
            dispatch=lambda *a: a[:3], state0=None, rnd0=0, done0=False,
            start_round=0, max_rounds=10, stride=5, depth=2, donate=True,
            on_retire=lambda r, s: None,
        )


def test_pipeline_chunks_validation():
    with pytest.raises(ValueError, match="pipeline_chunks"):
        SimConfig(n=4, pipeline_chunks=0)
    with pytest.raises(ValueError, match="pipeline_chunks"):
        SimConfig(n=4, pipeline_chunks=65)


def test_driver_resume_at_max_rounds_observes_one_boundary():
    log, retired = [], []
    result = pipeline_mod.run_chunks(
        dispatch=_fake_dispatch(log),
        state0={"conv_at": None}, rnd0=50, done0=False,
        start_round=50, max_rounds=50, stride=10, depth=2,
        on_retire=lambda r, s: retired.append(r),
    )
    assert result.rounds == 50
    assert retired == [50]  # the serial loop also fires the hook once
