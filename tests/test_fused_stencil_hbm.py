"""HBM-streaming stencil engine (ops/fused_stencil_hbm.py), interpret mode.

Serves constant-degree wrap lattices (torus3d/ring) past the VMEM-resident
stencil2 engine's budget; tests force it at small populations by shrinking
that budget. Oracles: gossip bitwise vs the chunked stencil path on both
the Z>0 (mod-n blend) and aligned paths, push-sum round equality, the
arithmetic displacement columns vs the builder's adjacency, gating.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import fused_stencil, fused_stencil_hbm

# Interpret-mode Pallas oracle: bitwise engine validation that cannot
# fit the ROADMAP tier-1 wall-clock budget on a CPU-only container (the
# kernels run under the Pallas interpreter). Full-suite / TPU runs
# execute it: `pytest tests/` (no -m filter) or `pytest -m slow`.
pytestmark = pytest.mark.slow


@pytest.fixture
def force_hbm(monkeypatch):
    monkeypatch.setattr(fused_stencil, "_VMEM_BUDGET", 1000)


def _cfg(n, kind, algorithm="gossip", engine="fused", **kw):
    kw.setdefault("max_rounds", 20000)
    kw.setdefault("chunk_rounds", 16)
    return SimConfig(n=n, topology=kind, algorithm=algorithm,
                     engine=engine, **kw)


@pytest.mark.parametrize("kind,n,semantics", [
    ("torus3d", 27_000, "batched"),   # g=30, all-live wrap columns
    ("grid3d", 27_000, "batched"),    # boundary-masked faces
    ("grid2d", 26_896, "batched"),    # 164^2
    ("line", 5_000, "batched"),
    ("ring", 5_000, "batched"),
    # Reference mode appends an unwired degree-0 node (Q1): the n_lat
    # detection must force its live masks empty.
    ("grid3d", 27_000, "reference"),
    ("grid2d", 26_896, "reference"),
    ("ref2d", 5_000, "reference"),
])
def test_arithmetic_columns_match_builder(kind, n, semantics):
    # The in-kernel (live, displacement) direction pairs must reproduce
    # the builder's adjacency exactly: the j-th LIVE pair in builder order
    # is neighbor column j — the bit-compat foundation for sampling.
    topo = build_topology(kind, n, semantics=semantics)
    n = topo.n
    dirs, _wrap = fused_stencil_hbm._lattice_params(topo)
    idx = jnp.arange(n, dtype=jnp.int32)[None, :]
    pairs = [(np.asarray(l).reshape(-1)[:n], np.asarray(d).reshape(-1)[:n])
             for l, d in dirs(idx)]
    ids = np.arange(n, dtype=np.int64)
    got = np.full((n, topo.max_deg), -1, dtype=np.int64)
    live_count = np.zeros(n, dtype=np.int64)
    for live, disp in pairs:
        rows = np.nonzero(live)[0]
        got[rows, live_count[rows]] = disp.astype(np.int64)[rows]
        live_count += live
    assert (live_count == topo.degree).all()
    want = np.where(
        np.arange(topo.max_deg)[None, :] < topo.degree[:, None],
        (topo.neighbors.astype(np.int64) - ids[:, None]) % n,
        -1,
    )
    assert (got == want).all(), kind


@pytest.mark.parametrize("kind,n,cap", [
    ("torus3d", 125000, 3000),   # wrap, Z > 0 (mod-n blend)
    ("ring", 65536, 400),        # wrap, Z = 0
    ("grid3d", 125000, 3000),    # non-wrap: boundary masks, signed shifts
    ("grid2d", 65536, 500),      # non-wrap, 2 offset classes, Z > 0 pad
    ("line", 20000, 300),        # chain wiring, degree 1 at the ends
])
def test_hbm_gossip_matches_chunked_bitwise(kind, n, cap, force_hbm):
    # ring/line/grid rows are round-capped: full convergence needs up to
    # ~30k interpret-mode rounds (~minutes) for no extra coverage over the
    # bounded comparison.
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology(kind, n),
                _cfg(n, kind, engine=engine, max_rounds=cap))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    if kind == "torus3d":
        assert a.converged and b.converged
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


def test_hbm_gossip_suppression_bitwise(force_hbm):
    n = 125000
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology("torus3d", n),
                _cfg(n, "torus3d", engine=engine, suppress_converged=True,
                     max_rounds=3000))
        results[engine] = r
    assert results["chunked"].rounds == results["fused"].rounds
    assert results["chunked"].converged_count == results["fused"].converged_count


@pytest.mark.parametrize("kind", ["torus3d", "grid3d"])
def test_hbm_pushsum_matches_chunked_fixed_rounds(kind, force_hbm):
    # Bounded rounds: interpret-mode push-sum to convergence at this size
    # costs minutes; 64 fixed rounds pin the trajectory STATE equivalence
    # (not just the vacuous round count). grid3d adds the boundary-masked
    # degree-varying sampling + signed-shift delivery to the pinned set.
    n = 125000
    final = {}

    def grab(tag):
        def f(rounds, state):
            final[tag] = state
        return f

    for engine in ["chunked", "fused"]:
        r = run(build_topology(kind, n),
                _cfg(n, kind, algorithm="push-sum", engine=engine,
                     max_rounds=64, chunk_rounds=64),
                on_chunk=grab(engine))
        assert r.rounds == 64
    a, b = final["chunked"], final["fused"]
    np.testing.assert_allclose(np.asarray(a.s), np.asarray(b.s)[:n],
                               rtol=2e-5, atol=1e-3)
    np.testing.assert_allclose(np.asarray(a.w), np.asarray(b.w)[:n],
                               rtol=2e-5, atol=1e-6)
    sm = float(np.asarray(b.s, np.float64)[:n].sum())
    true = n * (n - 1) / 2
    assert abs(sm - true) / true < 1e-5  # mass conserved through the kernel


def test_hbm_resume_midway(force_hbm):
    n = 125000
    cfg = _cfg(n, "torus3d", chunk_rounds=32, max_rounds=3000)
    topo = build_topology("torus3d", n)
    snaps = []
    full = run(topo, cfg, on_chunk=lambda r, s: snaps.append((r, s)))
    assert len(snaps) >= 2
    r0, s0 = snaps[0]
    resumed = run(topo, cfg, start_state=jax.tree.map(jnp.asarray, s0),
                  start_round=r0)
    assert resumed.rounds == full.rounds
    assert resumed.converged_count == full.converged_count


def test_hbm_support_gating():
    cfg = _cfg(125000, "torus3d")
    assert fused_stencil_hbm.stencil_hbm_support(
        build_topology("torus3d", 125000), cfg
    ) is None
    # Non-wrap lattices are served since r4 (VERDICT r3 #2b)...
    assert fused_stencil_hbm.stencil_hbm_support(
        build_topology("grid2d", 1024), cfg
    ) is None
    # ...imp kinds still are not (their long-range edges have no
    # arithmetic column; the HBM imp engine serves them).
    assert "arithmetic" in fused_stencil_hbm.stencil_hbm_support(
        build_topology("imp2d", 1024), cfg
    )
    assert "single-device" in fused_stencil_hbm.stencil_hbm_support(
        build_topology("torus3d", 125000),
        _cfg(125000, "torus3d", n_devices=4),
    )


def test_dispatch_routes_hbm_past_stencil2_budget(monkeypatch, force_hbm):
    from cop5615_gossip_protocol_tpu.models import runner as runner_mod

    seen = {}
    real = runner_mod._run_fused

    def spy(topo, cfg, key, on_chunk, start_state, start_round, interpret,
            variant="stencil"):
        seen["variant"] = variant
        return real(topo, cfg, key, on_chunk, start_state, start_round,
                    interpret, variant=variant)

    monkeypatch.setattr(runner_mod, "_run_fused", spy)
    r = run(build_topology("torus3d", 125000),
            _cfg(125000, "torus3d", max_rounds=3000))
    assert r.converged
    assert seen == {"variant": "stencil_hbm"}
