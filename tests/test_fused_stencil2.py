"""Tiled stencil engine (ops/fused_stencil.py), interpret mode on CPU.

The engine exists for populations the v1 whole-array engine refuses —
n > 131,072 and wraparound topologies at n % 128 != 0 — so every config
here is chosen to be v1-ineligible, making engine='fused' route through
stencil2. Oracles mirror tests/test_fused.py: gossip bitwise vs the
chunked XLA stencil path, push-sum on rounds/estimates, resume, gating.
"""

import jax
import jax.numpy as jnp
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import fused, fused_stencil

# Interpret-mode Pallas oracle: bitwise engine validation that cannot
# fit the ROADMAP tier-1 wall-clock budget on a CPU-only container (the
# kernels run under the Pallas interpreter). Full-suite / TPU runs
# execute it: `pytest tests/` (no -m filter) or `pytest -m slow`.
pytestmark = pytest.mark.slow


def _cfg(n, kind, algorithm="gossip", engine="fused", **kw):
    kw.setdefault("max_rounds", 200_000)
    kw.setdefault("chunk_rounds", 32)
    return SimConfig(n=n, topology=kind, algorithm=algorithm,
                     engine=engine, **kw)


def test_v1_refuses_these_configs():
    # Guard the premise: every config below is v1-ineligible, so
    # engine='fused' exercises stencil2.
    topo = build_topology("torus3d", 1000)  # pop 729, wrap + unaligned
    assert fused.fused_support(topo, _cfg(1000, "torus3d")) is not None
    assert fused_stencil.stencil2_support(topo, _cfg(1000, "torus3d")) is None


@pytest.mark.parametrize("kind,n", [("torus3d", 1000), ("ring", 300)])
def test_stencil2_gossip_matches_chunked_bitwise(kind, n):
    # Wraparound displacements at n % 128 != 0 — the exact case the v1
    # engine's padded-space rolls cannot express; the tiled engine's mod-n
    # blend must reproduce the chunked trajectory bit-for-bit.
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology(kind, n), _cfg(n, kind, engine=engine))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.converged and b.converged
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


def test_stencil2_gossip_suppression():
    n = 1000  # torus pop 729
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology("torus3d", n),
                _cfg(n, "torus3d", engine=engine, suppress_converged=True))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


def test_stencil2_pushsum_matches_chunked():
    n = 1000  # torus pop 729
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology("torus3d", n),
                _cfg(n, "torus3d", algorithm="push-sum", engine=engine,
                     chunk_rounds=256))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.converged and b.converged
    assert a.rounds == b.rounds
    assert abs(a.estimate_mae - b.estimate_mae) < 1e-3


def test_stencil2_resume_midway():
    n = 1000
    cfg = _cfg(n, "torus3d", chunk_rounds=8)
    topo = build_topology("torus3d", n)
    snaps = []
    full = run(topo, cfg, on_chunk=lambda r, s: snaps.append((r, s)))
    assert len(snaps) >= 2
    r0, s0 = snaps[0]
    resumed = run(topo, cfg, start_state=jax.tree.map(jnp.asarray, s0),
                  start_round=r0)
    assert resumed.rounds == full.rounds
    assert resumed.converged_count == full.converged_count


@pytest.mark.parametrize("chunk_rounds", [5, 100])
def test_stencil2_chunk_rounds_not_multiple_of_8(chunk_rounds):
    n = 1000
    a = run(build_topology("torus3d", n), _cfg(n, "torus3d", engine="chunked"))
    b = run(build_topology("torus3d", n),
            _cfg(n, "torus3d", chunk_rounds=chunk_rounds))
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


def test_stencil2_support_gating():
    # imp3d has random long-range edges — no displacement structure.
    topo = build_topology("imp3d", 1000)
    assert "displacement" in fused_stencil.stencil2_support(
        topo, _cfg(1000, "imp3d")
    )
    # Budget: a torus past the VMEM plane budget is refused with the reason
    # — and the HBM-streaming stencil tier picks it up instead of the old
    # hard failure (ops/fused_stencil_hbm.py).
    from cop5615_gossip_protocol_tpu.ops import fused_stencil_hbm

    big = build_topology("torus3d", 8_000_000)
    assert "budget" in fused_stencil.stencil2_support(
        big, _cfg(8_000_000, "torus3d")
    )
    assert fused_stencil_hbm.stencil_hbm_support(
        big, _cfg(8_000_000, "torus3d")
    ) is None
    # A config no fused tier serves (fault injection) still fails loudly.
    with pytest.raises(ValueError, match="unavailable"):
        run(big, _cfg(8_000_000, "torus3d", fault_rate=0.1))


def test_v1_still_preferred_where_eligible(monkeypatch):
    # Small aligned configs keep the proven v1 engine.
    from cop5615_gossip_protocol_tpu.models import runner as runner_mod

    seen = {}
    real = runner_mod._run_fused

    def spy(topo, cfg, key, on_chunk, start_state, start_round, interpret,
            variant="stencil"):
        seen["variant"] = variant
        return real(topo, cfg, key, on_chunk, start_state, start_round,
                    interpret, variant=variant)

    monkeypatch.setattr(runner_mod, "_run_fused", spy)
    r = run(build_topology("grid2d", 144),
            _cfg(144, "grid2d", max_rounds=4000))
    assert r.converged
    assert seen == {"variant": "stencil"}
