"""Continuous batching + the worker fleet (ISSUE 14).

The engine contract: ``models.sweep.serve_lanes`` retires lanes at chunk
boundaries and refills them with fresh requests, and every request's
result stays BITWISE the one-shot ``models.runner.run`` — filler lanes,
refilled lanes and per-lane deadlines included. The serving contract: the
batcher's continuous executor keeps the accounting identities exact
under refill churn, including a deadline expiring on a request that was
about to be refilled. The fleet contract: consistent-hash routing is
stable, and removing a worker moves only its own buckets.
"""

import threading
import time

import numpy as np
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models import sweep
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.serving.admission import ServingStats
from cop5615_gossip_protocol_tpu.serving.batcher import MicroBatcher
from cop5615_gossip_protocol_tpu.serving.fleet import FleetFront, HashRing
from cop5615_gossip_protocol_tpu.serving.server import ServingApp


class ScriptedSource:
    """A list-backed lane source: hands out ``feed[k]`` per poll call (or
    everything remaining), collects results by tag."""

    def __init__(self, tickets, first_fill=None):
        self.todo = list(tickets)
        # Cap the FIRST poll's hand-out below the lane width to force a
        # filler lane that a later refill reclaims.
        self.first_fill = first_fill
        self.results = {}
        self.boundaries = 0

    def poll(self, k):
        if self.first_fill is not None:
            k = min(k, self.first_fill)
            self.first_fill = None
        out, self.todo = self.todo[:k], self.todo[k:]
        return out

    def on_result(self, ticket, res):
        assert ticket.tag not in self.results, "double result for a lane"
        self.results[ticket.tag] = res

    def on_boundary(self, active, lanes):
        self.boundaries += 1
        return True


def _gossip_cfg(seed, **kw):
    kw.setdefault("rumor_threshold", 5)
    kw.setdefault("chunk_rounds", 4)
    return SimConfig(n=32, topology="full", algorithm="gossip", seed=seed,
                     engine="chunked", **kw)


def _one_shot_state(cfg, topo):
    cap = {}

    def hook(rounds, state):
        import jax

        cap["state"] = jax.tree.map(np.asarray, state)

    res = run(topo, cfg, on_chunk=hook)
    return res, cap["state"]


def test_serve_lanes_gossip_bitwise_under_refill_churn():
    """The tentpole parity pin: 8 requests through 2 lanes — every
    result past the first two is a REFILLED lane at a non-zero round
    offset, and each must be bitwise the one-shot runner.run (state +
    telemetry), exactly like a wave lane."""
    topo = build_topology("full", 32)
    seeds = [3, 11, 42, 7, 99, 123, 5, 6]
    src = ScriptedSource([sweep.LaneTicket(key=s, tag=s) for s in seeds])
    summary = sweep.serve_lanes(
        topo, _gossip_cfg(seeds[0], telemetry=True), src, lanes=2
    )
    assert summary.served == len(seeds)
    assert summary.refills == len(seeds) - 2
    for s in seeds:
        res = src.results[s]
        one, state = _one_shot_state(
            _gossip_cfg(s, telemetry=True), topo
        )
        assert res.outcome == "converged" and res.converged
        assert res.rounds == one.rounds, s
        for f in state._fields:
            np.testing.assert_array_equal(
                getattr(res.state, f), getattr(state, f),
                err_msg=f"seed {s} field {f}",
            )
        np.testing.assert_array_equal(
            res.telemetry.data, one.telemetry.data,
            err_msg=f"seed {s} telemetry",
        )


def test_serve_lanes_filler_lane_reclaimed_bitwise():
    """A lane that starts as FILLER (initial fill below the width) and is
    reclaimed by a later refill must serve its request bitwise too."""
    topo = build_topology("full", 32)
    seeds = [21, 22, 23, 24, 25]
    src = ScriptedSource(
        [sweep.LaneTicket(key=s, tag=s) for s in seeds], first_fill=3
    )
    summary = sweep.serve_lanes(topo, _gossip_cfg(seeds[0]), src, lanes=4)
    assert summary.served == len(seeds)
    for s in seeds:
        one, state = _one_shot_state(_gossip_cfg(s), topo)
        assert src.results[s].rounds == one.rounds, s
        for f in state._fields:
            np.testing.assert_array_equal(
                getattr(src.results[s].state, f), getattr(state, f),
                err_msg=f"seed {s} field {f}",
            )


def test_serve_lanes_pushsum_bitwise_and_mae():
    topo = build_topology("full", 32)
    seeds = [5, 6, 7, 8]

    def cfg(s):
        return SimConfig(n=32, topology="full", algorithm="push-sum",
                         seed=s, engine="chunked", delta=1e-3,
                         chunk_rounds=8)

    src = ScriptedSource([sweep.LaneTicket(key=s, tag=s) for s in seeds])
    sweep.serve_lanes(topo, cfg(seeds[0]), src, lanes=2)
    for s in seeds:
        one, state = _one_shot_state(cfg(s), topo)
        res = src.results[s]
        assert res.rounds == one.rounds, s
        for f in state._fields:
            np.testing.assert_array_equal(
                getattr(res.state, f), getattr(state, f),
                err_msg=f"seed {s} field {f}",
            )
        assert res.estimate_mae == pytest.approx(one.estimate_mae,
                                                 rel=1e-5)


def test_serve_lanes_deadline_kills_and_refills_the_lane():
    """Per-lane deadlines are clock-only and refill-aware: an expired
    lane retires with a partial-but-exact result at the next boundary,
    its slot is reclaimed by the waiting ticket, and the accounting sums
    (one result per ticket, refills counted)."""
    topo = build_topology("full", 32)
    # Unreachable threshold: lanes run until their own deadline fires.
    cfg = _gossip_cfg(0, rumor_threshold=10**6, max_rounds=10**4,
                      chunk_rounds=2)
    now = time.monotonic()
    src = ScriptedSource([
        sweep.LaneTicket(key=1, tag="a", deadline=now + 0.15),
        sweep.LaneTicket(key=2, tag="b", deadline=now + 0.35),
    ])
    summary = sweep.serve_lanes(topo, cfg, src, lanes=1)
    assert summary.served == 2 and summary.refills == 1
    for tag in ("a", "b"):
        res = src.results[tag]
        assert res.outcome == "deadline_exceeded", tag
        assert not res.converged
        assert 0 < res.rounds < 10**4
    # An already-expired ticket retires at the FIRST boundary after fill.
    src2 = ScriptedSource([
        sweep.LaneTicket(key=3, tag="dead",
                         deadline=time.monotonic() - 1.0),
        sweep.LaneTicket(key=4, tag="live"),
    ])
    summary2 = sweep.serve_lanes(topo, _gossip_cfg(9), src2, lanes=2)
    assert src2.results["dead"].outcome == "deadline_exceeded"
    assert src2.results["live"].outcome == "converged"
    assert summary2.served == 2


def test_serve_lanes_poll_overflow_is_loud():
    topo = build_topology("full", 32)

    class Greedy(ScriptedSource):
        def poll(self, k):
            return [sweep.LaneTicket(key=i, tag=i) for i in range(k + 1)]

    with pytest.raises(ValueError, match="free lanes"):
        sweep.serve_lanes(topo, _gossip_cfg(0), Greedy([]), lanes=1)


# ------------------------------------------------- batcher continuous path


def test_batcher_continuous_refills_and_identities():
    """Six same-bucket requests through a 2-lane continuous executor: one
    acquisition serves all six (four refills), every response demuxes
    correctly, and the accounting identities stay exact under the
    churn."""
    app = ServingApp(window_s=0.05, max_lanes=2, min_lanes=1)
    try:
        results = [None] * 6

        def go(i):
            results[i] = app.handle_run({
                "schema_version": 1, "n": 32, "topology": "full",
                "algorithm": "gossip", "seed": 100 + i,
                "params": {"rumor_threshold": 5, "chunk_rounds": 4},
            })

        threads = [threading.Thread(target=go, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, (st, resp) in enumerate(results):
            assert st == 200, resp
            assert resp["result"]["outcome"] == "converged"
            assert resp["serving"]["continuous"] is True
            assert resp["serving"]["batch_lanes"] == 2
        snap = app.snapshot()
        assert snap["completed"] == 6 and snap["failed"] == 0
        assert snap["batched_requests"] == 6
        # One wave popped all six -> four of them refilled mid-acquisition
        # (the six may split across at most a few acquisitions under
        # scheduler jitter, but lanes=2 forces >= 1 refill overall).
        assert snap["refills"] >= 1
        assert snap["received"] == snap["admitted"] == 6
        assert snap["lane_fill_mean"] is not None
        # Per-request parity through the serving stack: each response
        # bitwise the one-shot run of its seed.
        topo = build_topology("full", 32)
        for i, (st, resp) in enumerate(results):
            one = run(topo, _gossip_cfg(100 + i))
            assert resp["result"]["rounds"] == one.rounds
            assert (resp["result"]["converged_count"]
                    == one.converged_count)
    finally:
        app.close()


def test_pop_bucket_requests_sheds_expired_deadline_at_refill():
    """The satellite accounting pin: a deadline that expires on a
    request WAITING to be refilled is shed at the refill hand-off (504,
    never dispatched), and the identities hold exactly."""
    stats = ServingStats()
    b = MicroBatcher(stats=stats, min_lanes=1, window_s=0.01)
    # NOT started: requests stay queued; we drive the refill pop by hand.
    fresh = b.submit(_gossip_cfg(0), False)
    expired = b.submit(_gossip_cfg(1), False, deadline_ms=1)
    time.sleep(0.02)  # the second request's 1 ms deadline lapses in queue
    popped = b._pop_bucket_requests(fresh.bucket, 2, gen=b._gen)
    assert popped == [fresh]
    assert fresh.is_dispatched() and not fresh.claimed
    assert expired.claimed and expired.status == 504
    assert expired.response["error"] == "deadline_exceeded"
    assert stats.shed == 1 and stats.deadline_exceeded == 1
    # The occupancy ledger carries exactly the dispatched request so far.
    assert stats.batched_requests == 1
    snap = stats.snapshot()
    # received is the FRONT's counter (ServingApp._submit) — driving the
    # batcher directly, only the admitted-side identities apply. The
    # hand-popped request is dispatched-but-unresolved here (this unit
    # bypasses the executor), so the admitted identity closes through
    # in_flight; the occupancy identity closes once it resolves — the
    # end-to-end churn test above pins that at quiescence.
    assert snap["admitted"] == 2
    assert snap["in_flight"] == 1
    assert snap["admitted"] == (
        snap["completed"] + snap["failed"] + snap["shed"]
        + snap["timed_out"] + snap["in_flight"]
    )
    b.stop(drain=False)


def test_lane_budget_bounds_hostage_lanes(monkeypatch):
    """The continuous analog of the stuck-executor watchdog: a healthy
    acquisition heartbeats the watchdog at every boundary, so a
    stall-prone request with a huge max_rounds would otherwise hold its
    lane (and eventually the executor) hostage while looking live. The
    lane residency budget retires it with a structured partial result."""
    monkeypatch.setenv("GOSSIP_TPU_SERVE_LANE_BUDGET_S", "0.3")
    app = ServingApp(window_s=0.005, max_lanes=2, min_lanes=1)
    try:
        t0 = time.monotonic()
        st, resp = app.handle_run({
            "schema_version": 1, "n": 32, "topology": "full",
            "algorithm": "gossip", "seed": 0,
            # Unreachable threshold + huge round cap: would run ~1e6
            # rounds without the budget.
            "params": {"rumor_threshold": 10**6, "max_rounds": 10**6,
                       "chunk_rounds": 8},
        })
        elapsed = time.monotonic() - t0
        assert st == 200, resp
        assert resp["result"]["outcome"] == "deadline_exceeded"
        assert 0 < resp["result"]["rounds"] < 10**6
        assert elapsed < 5.0, elapsed
        snap = app.snapshot()
        assert snap["completed"] == 1 and snap["deadline_exceeded"] == 1
    finally:
        app.close()


def test_wave_mode_control_still_serves():
    """--no-continuous (the loadgen A/B control) keeps the PR 6 wave
    semantics working end to end."""
    app = ServingApp(window_s=0.01, max_lanes=4, min_lanes=1,
                     continuous=False)
    try:
        st, resp = app.handle_run({
            "schema_version": 1, "n": 32, "topology": "full",
            "algorithm": "gossip", "seed": 5,
        })
        assert st == 200 and resp["result"]["outcome"] == "converged"
        assert "continuous" not in resp["serving"]
        snap = app.snapshot()
        assert snap["completed"] == 1 and snap["refills"] == 0
    finally:
        app.close()


# ---------------------------------------------------------------- the fleet


def test_hash_ring_routes_deterministically_and_moves_minimally():
    ring = HashRing(vnodes=64)
    for w in ("w0", "w1", "w2"):
        ring.add(w)
    keys = [f"bucket-{i}" for i in range(200)]
    before = {k: ring.candidates(k)[0] for k in keys}
    assert before == {k: ring.candidates(k)[0] for k in keys}  # stable
    assert len(set(before.values())) == 3  # all workers hold arcs
    ring.remove("w1")
    after = {k: ring.candidates(k)[0] for k in keys}
    for k in keys:
        if before[k] != "w1":
            # Consistent hashing: only the dead worker's buckets move.
            assert after[k] == before[k], k
        else:
            assert after[k] in ("w0", "w2")
    # candidates() walks every live worker exactly once.
    cands = ring.candidates("bucket-0")
    assert sorted(cands) == ["w0", "w2"] and len(cands) == 2


class _StubWorker:
    def __init__(self, wid):
        self.worker_id = wid


def test_fleet_route_key_is_the_serve_bucket():
    front = FleetFront([_StubWorker("w0"), _StubWorker("w1")])
    body = {"schema_version": 1, "n": 32, "topology": "full",
            "algorithm": "gossip", "seed": 1}
    # Same bucket regardless of seed (fault-free) -> same routing key;
    # a different population is a different bucket.
    k1 = front.route_key(dict(body))
    k2 = front.route_key(dict(body, seed=99))
    k3 = front.route_key(dict(body, n=48))
    assert k1 == k2 != k3
    with pytest.raises(ValueError):
        front.route_key({"n": 32, "topology": "nope",
                         "algorithm": "gossip"})


def test_fleet_front_quarantine_membership_routes_around():
    front = FleetFront([_StubWorker(f"w{i}") for i in range(3)],
                       quarantine_s=60.0)
    rkey = "some-bucket"
    home = front._pick_workers(rkey)[0][0]
    front.quarantine.trip(home)
    cands = front._pick_workers(rkey)
    # The tripped worker is parked at the back; a healthy worker leads.
    assert cands[0][0] != home
    assert cands[-1][0] == home


def test_fleet_probe_token_survives_unrelated_routing():
    """Review fix: routing walks must NOT consume a quarantined worker's
    one half-open probe token unless the request actually attempts it —
    otherwise a recovered worker could never rejoin the ring."""
    front = FleetFront([_StubWorker(f"w{i}") for i in range(3)],
                       quarantine_s=60.0)
    rkey = "some-bucket"
    home = front._pick_workers(rkey)[0][0]
    # Cooldown 0: the circuit is immediately probe-eligible.
    front.quarantine.trip(home, cooldown_s=0.0)
    # Many unrelated routing walks before anyone probes: none may flip
    # the worker to half-open as a side effect...
    cands = front._pick_workers(rkey)
    # ...the FIRST walk after expiry hands the probe out, in front.
    assert cands[0] == (home, True)
    # While that probe is outstanding, later walks park the worker.
    again = front._pick_workers(rkey)
    assert again[0][0] != home and again[-1] == (home, False)
    # A successful probe report closes the circuit and rejoins the ring.
    front.quarantine.record(home, ok=True)
    assert front._pick_workers(rkey)[0] == (home, False)


def test_probe_dispatch_slices_oversize_continuous_group():
    """Review fix: the continuous executor hands UN-SLICED groups to
    _execute; when the bucket's circuit is half-open the group takes the
    wave (probe) path, which runs at most max_lanes keys per dispatch —
    an oversize group must be sliced, not failed as invalid-config."""
    stats = ServingStats()
    b = MicroBatcher(stats=stats, max_lanes=2, min_lanes=1,
                     window_s=0.001)
    # NOT started: we drive the executor path by hand.
    reqs = [b.submit(_gossip_cfg(300 + i), False) for i in range(5)]
    with b._cv:
        batch = b._pop_all_locked()
    # Half-open circuit: check() hands the probe to this dispatch.
    b.quarantine.trip(reqs[0].bucket, cooldown_s=0.0)
    b._execute_safe(batch, b._gen)
    for r in reqs:
        assert r.ready.is_set()
        assert r.status == 200, r.response
        assert r.response["result"]["outcome"] == "converged"
    # The probe succeeded: the circuit closed.
    assert b.quarantine.state(reqs[0].bucket) == "closed"
    assert stats.completed == 5 and stats.failed == 0
    assert stats.batched_requests == 5
    b.stop(drain=False)


@pytest.mark.slow
def test_fleet_end_to_end_with_worker_kill():
    """Real OS-process fleet: routing, the multi-worker envelope split,
    and a worker KILL mid-session — the dead worker's buckets re-route
    and the front's received == responded identity holds exactly (the
    chaos-fleet CI job drives the same contract under load)."""
    from cop5615_gossip_protocol_tpu.serving.fleet import spawn_workers

    workers = spawn_workers(
        2, ["--platform", "cpu", "--window-ms", "2", "--max-lanes", "16"]
    )
    front = FleetFront(workers, quarantine_s=1.0)
    try:
        body = {"schema_version": 1, "n": 32, "topology": "full",
                "algorithm": "gossip", "seed": 1}
        r = front.handle_body(dict(body))
        assert r["status"] == 200, r
        home = r["fleet"]["worker"]
        env = front.handle_envelope({"requests": [
            dict(body, seed=s) for s in range(4)
        ] + [
            {"schema_version": 1, "n": 36, "topology": "grid2d",
             "algorithm": "gossip", "seed": 9},
        ]})
        assert env["status"] == 200
        assert all(m["status"] == 200 for m in env["responses"]), env
        # Same bucket -> same worker (warm-pool locality); the grid2d
        # bucket may land elsewhere.
        assert {m["fleet"]["worker"] for m in env["responses"][:4]} == {
            home
        }
        victim = front.workers[home]
        victim.proc.kill()
        victim.proc.wait(timeout=10)
        survivor = next(w for w in workers if w.worker_id != home)
        for s in range(10, 14):
            r = front.handle_body(dict(body, seed=s))
            assert r["status"] == 200, r
            assert r["fleet"]["worker"] == survivor.worker_id
        snap = front.snapshot()
        assert snap["front"]["received"] == snap["front"]["responded"]
        assert snap["front"]["worker_failures"] >= 1
        assert snap["workers"][home] == {"alive": False}
    finally:
        for w in workers:
            if w.proc.poll() is None:
                w.shutdown()
