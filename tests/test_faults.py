"""Failure-model subsystem (ops/faults.py): crash-stop churn, quorum
termination, message-level faults, and the stall watchdog.

The reference models zero faults and hangs on a stalled topology
(program.fs:334); these tests pin the semantics the failure subsystem
promises instead:

- crash schedules and rates produce a deterministic death plane, rebuilt
  from the config alone on every engine;
- a crash-schedule push-sum run terminates via quorum over LIVE nodes with
  total mass (live + dead — dead nodes park delivered mass) conserved to
  <= 1 ulp at float64;
- the drop gate + crash plane run IN-KERNEL on the fused tiers, matching
  the chunked XLA engine round for round (the regenerated threefry gate is
  the same stream ops/sampling.send_gate draws);
- dup/delay message faults conserve mass over state + in-flight ring;
- the stall watchdog turns the reference's line-topology hang into a
  measured outcome="stalled" record;
- checkpoint-resume of a faulted run follows the original trajectory
  bitwise (the death plane is derived from the config, never stored).
"""

import numpy as np
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import faults


# ---------------------------------------------------------------- plumbing


def test_parse_crash_schedule():
    assert faults.parse_crash_schedule("5:10") == ((5, 10),)
    assert faults.parse_crash_schedule("9:1, 3:7") == ((3, 7), (9, 1))
    for bad in ["", "5", "5:0", "-1:3", "5:2,5:3", "a:b", "5:10:2"]:
        with pytest.raises(ValueError):
            faults.parse_crash_schedule(bad)


def test_config_failure_model_validation():
    with pytest.raises(ValueError, match="mutually exclusive"):
        SimConfig(n=64, topology="full", crash_rate=0.1, crash_schedule="5:3")
    # quorum < 1.0 without a crash model is a no-op, not an invalid config:
    # it must warn LOUDLY (stderr via the CLI, RuntimeWarning for API
    # users) instead of erroring or silently ignoring.
    with pytest.warns(RuntimeWarning, match="quorum"):
        cfg = SimConfig(n=64, topology="full", quorum=0.9)  # no crash model
    assert any("quorum" in w for w in cfg.lint_warnings)
    assert SimConfig(n=64, topology="full").lint_warnings == ()
    with pytest.raises(ValueError, match="reference"):
        SimConfig(n=64, topology="full", semantics="reference", crash_rate=0.1)
    with pytest.raises(ValueError, match="global"):
        SimConfig(n=64, topology="full", algorithm="push-sum",
                  crash_rate=0.1, termination="global")
    # config-time schedule validation, not first-run
    with pytest.raises(ValueError, match="round:count"):
        SimConfig(n=64, topology="full", crash_schedule="nope")


def test_death_plane_deterministic_and_schedule_exact():
    cfg = SimConfig(n=256, topology="full", crash_schedule="4:30,9:20")
    d1 = faults.death_plane(cfg, 256)
    d2 = faults.death_plane(cfg, 256)
    assert (d1 == d2).all()  # pure function of (cfg, n)
    assert (d1 == 4).sum() == 30 and (d1 == 9).sum() == 20
    assert (d1 == faults.NEVER).sum() == 256 - 50
    # alive_at: nodes with death round r are dead DURING round r; the
    # round-9 cohort is still alive at round 4.
    assert int(np.asarray(faults.alive_at(d1, 3)).sum()) == 256
    assert int(np.asarray(faults.alive_at(d1, 4)).sum()) == 256 - 30
    assert int(np.asarray(faults.alive_at(d1, 9)).sum()) == 256 - 50
    assert faults.death_plane(
        SimConfig(n=256, topology="full"), 256
    ) is None


def test_quorum_need_integer_exact_at_full_quorum():
    # ceil(1.0 * alive) at float32 is off by one above 2^24; the
    # alive - floor((1-q)*alive) form is exact.
    for alive in [1, 7, 2**24 + 1, 2**26]:
        assert int(faults.quorum_need(alive, 1.0)) == alive
    assert int(faults.quorum_need(100, 0.9)) == 90
    assert int(faults.quorum_need(10, 0.95)) == 10  # floor(0.5) = 0


# ------------------------------------------- crash + quorum + conservation


def _total_mass(state):
    return (
        np.asarray(state.s, np.float64).sum(),
        np.asarray(state.w, np.float64).sum(),
    )


def test_crash_schedule_pushsum_quorum_conserves_mass():
    # Acceptance pin: a crash-schedule push-sum run terminates via quorum
    # (not max_rounds) and total mass over live + dead nodes is conserved
    # to <= 1 ulp — dead nodes park delivered mass, they don't destroy it.
    n = 512
    cfg = SimConfig(n=n, topology="full", delivery="pool",
                    algorithm="push-sum", engine="chunked",
                    crash_schedule="3:100,6:50", quorum=0.95, fault_rate=0.3,
                    max_rounds=8000, chunk_rounds=32, dtype="float64")
    cap = {}
    r = run(build_topology("full", n), cfg,
            on_chunk=lambda rounds, st: cap.update(state=st))
    assert r.converged and r.outcome == "converged"
    assert r.rounds < cfg.max_rounds
    # 150 dead nodes can never converge; quorum counts live ones only.
    death = faults.death_plane(cfg, n)
    alive = death > (r.rounds - 1)
    assert int(alive.sum()) == n - 150
    assert r.converged_count >= int(faults.quorum_need(int(alive.sum()), 0.95))
    s_tot, w_tot = _total_mass(cap["state"])
    s0, w0 = n * (n - 1) / 2.0, float(n)
    assert abs(s_tot - s0) <= np.spacing(s0)
    assert abs(w_tot - w0) <= np.spacing(w0)


def test_crash_rate_churn_terminates_with_quorum():
    # Geometric churn: every node independently survives each round with
    # probability 1-p. Fixed seed -> deterministic death plane; the run
    # must end via quorum instead of spinning to max_rounds.
    n = 256
    cfg = SimConfig(n=n, topology="full", delivery="pool",
                    algorithm="push-sum", engine="chunked", crash_rate=0.002,
                    quorum=0.7, max_rounds=8000, chunk_rounds=32, seed=7)
    r = run(build_topology("full", n), cfg)
    assert r.converged and r.outcome == "converged"
    assert r.rounds < cfg.max_rounds


def test_crash_gossip_sharded_matches_single_device():
    # The sharded runner slices the SAME death plane per shard (padded
    # slots count as dead) and runs the quorum psum in-trace — rounds must
    # match the single-device chunked engine exactly (integer gossip
    # state, identical stream), device count notwithstanding.
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh
    from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded

    n = 512
    cfg = SimConfig(n=n, topology="full", algorithm="gossip",
                    crash_schedule="2:120", quorum=0.9, fault_rate=0.1,
                    max_rounds=6000, chunk_rounds=32)
    topo = build_topology("full", n)
    a = run(topo, cfg)
    b = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count
    assert a.converged and b.converged
    assert a.outcome == b.outcome == "converged"


# ------------------------------------------------------- dup/delay faults


def test_dup_rate_inflates_gossip_receipts():
    # At-least-once delivery: duplicated rumor receipts only speed the
    # count toward the threshold — convergence still happens, and the
    # faulted trajectory differs from the exact-once one.
    n = 256
    base = dict(n=n, topology="full", algorithm="gossip", engine="chunked",
                max_rounds=6000, chunk_rounds=32)
    a = run(build_topology("full", n), SimConfig(**base))
    b = run(build_topology("full", n), SimConfig(dup_rate=0.5, **base))
    assert a.converged and b.converged
    assert b.rounds <= a.rounds  # duplicates never slow the rumor down


def test_delay_ring_conserves_mass_in_flight():
    # Bounded message delay: deliveries park in the D-deep ring before
    # absorption, so at any chunk boundary Σmass(state) alone is down by
    # the in-flight planes but Σmass(state) + Σmass(ring) is exact. The
    # runner only exposes the state, so pin the observable consequences:
    # convergence still happens and the estimate is still the true mean
    # (mass was delayed, never destroyed).
    n = 256
    cfg = SimConfig(n=n, topology="full", algorithm="push-sum",
                    engine="chunked", delay_rounds=3, max_rounds=8000,
                    chunk_rounds=32, dtype="float64")
    cap = {}
    r = run(build_topology("full", n), cfg,
            on_chunk=lambda rounds, st: cap.update(state=st))
    assert r.converged
    assert r.estimate_mae < 1e-6
    # At termination every ring slot has been drained into some node's
    # (s, w) or still rides the ring; the state total can be short by at
    # most the in-flight fraction but never exceeds the initial total.
    s_tot, w_tot = _total_mass(cap["state"])
    assert s_tot <= n * (n - 1) / 2.0 + np.spacing(n * (n - 1) / 2.0)
    assert w_tot <= n + np.spacing(float(n))


def test_delay_rejects_resume():
    cfg = SimConfig(n=64, topology="full", algorithm="push-sum",
                    engine="chunked", delay_rounds=2, max_rounds=100)
    topo = build_topology("full", 64)
    from cop5615_gossip_protocol_tpu.models import pushsum

    st = pushsum.init_state(64, np.float32, 0)
    with pytest.raises(ValueError, match="delay_rounds"):
        run(topo, cfg, start_state=st, start_round=10)


# ---------------------------------------------------------- stall watchdog


def test_watchdog_reports_stalled_line_gossip():
    # The reference's famous line-topology hang as a measured event: with
    # the drop gate this hot, the rumor never leaves the leader, the
    # converged count makes no progress, and the watchdog ends the run
    # with outcome="stalled" instead of spinning to max_rounds.
    n = 128
    cfg = SimConfig(n=n, topology="line", algorithm="gossip",
                    engine="chunked", fault_rate=0.9999, stall_chunks=3,
                    chunk_rounds=32, max_rounds=100000)
    r = run(build_topology("line", n), cfg)
    assert r.outcome == "stalled"
    assert not r.converged
    assert r.rounds < cfg.max_rounds  # ended early, not at the cap


def test_watchdog_off_runs_to_max_rounds():
    n = 128
    cfg = SimConfig(n=n, topology="line", algorithm="gossip",
                    engine="chunked", fault_rate=0.9999, stall_chunks=0,
                    chunk_rounds=32, max_rounds=256)
    r = run(build_topology("line", n), cfg)
    assert r.outcome == "max_rounds"
    assert r.rounds == 256


def test_outcome_in_jsonl_record():
    from cop5615_gossip_protocol_tpu.utils import metrics

    n = 128
    cfg = SimConfig(n=n, topology="line", algorithm="gossip",
                    engine="chunked", fault_rate=0.9999, stall_chunks=3,
                    chunk_rounds=32, max_rounds=100000)
    topo = build_topology("line", n)
    rec = metrics.run_record(cfg, topo, run(topo, cfg))
    assert rec["outcome"] == "stalled"


# ------------------------------------------------- checkpoint-resume pins


def test_checkpoint_resume_faulted_run_bitwise(tmp_path):
    # A faulted (drop + crash) run resumed from a mid-run checkpoint must
    # follow the original trajectory bitwise: the gate stream is absolute-
    # round keyed and the death plane is rebuilt from the config (never
    # stored in the .npz).
    from cop5615_gossip_protocol_tpu.utils import checkpoint as ckpt

    n = 256
    cfg = SimConfig(n=n, topology="full", delivery="pool",
                    algorithm="push-sum", engine="chunked",
                    crash_schedule="3:60", quorum=0.9, fault_rate=0.2,
                    max_rounds=8000, chunk_rounds=16)
    topo = build_topology("full", n)
    snaps = {}
    full_cap = {}

    def hook(rounds, st):
        full_cap.update(state=st, rounds=rounds)
        if rounds == 32:
            snaps[32] = st

    r_full = run(topo, cfg, on_chunk=hook)
    assert r_full.converged and 32 in snaps
    path = tmp_path / "faulted.npz"
    ckpt.save(path, snaps[32], 32, cfg)
    st, rounds, saved_cfg = ckpt.load(path)
    assert rounds == 32 and saved_cfg == cfg

    cap2 = {}
    r_res = run(topo, cfg, start_state=st, start_round=rounds,
                on_chunk=lambda rd, s: cap2.update(state=s))
    assert r_res.rounds == r_full.rounds
    assert r_res.converged_count == r_full.converged_count
    a, b = full_cap["state"], cap2["state"]
    assert (np.asarray(a.s) == np.asarray(b.s)).all()
    assert (np.asarray(a.w) == np.asarray(b.w)).all()
    assert (np.asarray(a.conv) == np.asarray(b.conv)).all()


def test_resumed_quorum_run_executes_zero_rounds_when_done(tmp_path):
    # A checkpoint taken at/after quorum convergence must execute ZERO
    # further rounds on resume — the host-side done predicate re-evaluates
    # the quorum rule, not the legacy full-count target (which 60 dead
    # nodes make permanently unreachable).
    n = 256
    cfg = SimConfig(n=n, topology="full", delivery="pool",
                    algorithm="push-sum", engine="chunked",
                    crash_schedule="3:60", quorum=0.9, fault_rate=0.2,
                    max_rounds=8000, chunk_rounds=16)
    topo = build_topology("full", n)
    cap = {}
    r = run(topo, cfg, on_chunk=lambda rd, st: cap.update(state=st))
    assert r.converged
    r2 = run(topo, cfg, start_state=cap["state"], start_round=r.rounds)
    assert r2.rounds == r.rounds  # zero extra rounds
    assert r2.converged and r2.outcome == "converged"


# --------------------------------------- fused stencil engine fault parity


def test_fused_stencil_drop_gate_matches_chunked_bitwise():
    # Acceptance pin: --fault-rate accepted by the stencil fused engine
    # (ops/fused.py), with the in-kernel regenerated threefry gate matching
    # ops/sampling.send_gate word for word — integer gossip state, so
    # round-count + converged-count equality IS bitwise trajectory
    # equality.
    n = 144
    results = {}
    for engine in ["chunked", "fused"]:
        cfg = SimConfig(n=n, topology="grid2d", algorithm="gossip",
                        engine=engine, fault_rate=0.2, max_rounds=4000,
                        chunk_rounds=48)
        results[engine] = run(build_topology("grid2d", n), cfg)
    a, b = results["chunked"], results["fused"]
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count
    assert a.converged and b.converged


def test_fused_stencil_crash_quorum_matches_chunked():
    n = 144
    results = {}
    for engine in ["chunked", "fused"]:
        cfg = SimConfig(n=n, topology="grid2d", algorithm="gossip",
                        engine=engine, crash_schedule="5:20", quorum=0.9,
                        max_rounds=4000, chunk_rounds=48)
        results[engine] = run(build_topology("grid2d", n), cfg)
    a, b = results["chunked"], results["fused"]
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count
    assert a.outcome == b.outcome == "converged"
