"""Serving resilience plane (ISSUE 8): the run_chunks cancellation hook +
end-to-end deadlines (engine, sweep, serving, CLI), priority classes with
SLO-aware shedding, the stuck-executor watchdog -> failover -> quarantine
-> half-open -> recovery cycle, graceful-shutdown edges, and the
orphaned-timeout accounting identities."""

import json
import time

import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models import pipeline as pipeline_mod
from cop5615_gossip_protocol_tpu.models import sweep as sweep_mod
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.models.sweep import run_batched_keys
from cop5615_gossip_protocol_tpu.serving import pool as pool_mod
from cop5615_gossip_protocol_tpu.serving.admission import (
    AdmissionError,
    ServingStats,
)
from cop5615_gossip_protocol_tpu.serving.batcher import MicroBatcher
from cop5615_gossip_protocol_tpu.serving.server import ServingApp
from cop5615_gossip_protocol_tpu.utils import obs
from cop5615_gossip_protocol_tpu.utils.events import EVENT_SCHEMA_VERSION
from cop5615_gossip_protocol_tpu.utils.metrics import (
    RUN_RECORD_SCHEMA_VERSION,
)

# ------------------------------------------------- run_chunks cancellation


def _fake_dispatch(calls):
    """Host-int chunk: advances rnd to round_end, never terminates."""

    def dispatch(state, rnd, done, round_end):
        calls.append(int(round_end))
        return state + 1, int(round_end), False

    return dispatch


def test_run_chunks_cancel_stops_at_retired_boundary():
    calls = []
    fired = []

    def should_cancel(rounds):
        fired.append(rounds)
        return rounds >= 16

    loop = pipeline_mod.run_chunks(
        dispatch=_fake_dispatch(calls), state0=0, rnd0=0, done0=False,
        start_round=0, max_rounds=80, stride=8, depth=3,
        should_cancel=should_cancel,
    )
    assert loop.cancelled is True
    assert loop.rounds == 16  # exact: the retired boundary's counter
    # Cancellable loops run at depth 1 (the one-chunk cancel bound): no
    # speculative chunk was dispatched past the cancel boundary.
    assert calls == [8, 16]
    assert fired == [8, 16]
    assert loop.chunks_retired == 2


def test_run_chunks_without_hook_keeps_depth_and_reports_uncancelled():
    calls = []
    loop = pipeline_mod.run_chunks(
        dispatch=_fake_dispatch(calls), state0=0, rnd0=0, done0=False,
        start_round=0, max_rounds=24, stride=8, depth=2,
    )
    assert loop.cancelled is False
    assert loop.rounds == 24
    # Depth 2 honored: speculation dispatched ahead of the retire loop.
    assert calls[0:2] == [8, 16]


# ------------------------------------------------ engine deadline (runner)


def _slow_cfg(n=2048, **kw):
    return SimConfig(n=n, topology="line", algorithm="gossip", seed=0,
                     engine="chunked", chunk_rounds=8, max_rounds=6000,
                     **kw)


def test_deadline_exceeded_partial_telemetry_engine_free():
    """The ISSUE 8 deadline pin: a deadline far below the run length
    returns deadline_exceeded within deadline + one chunk + eps, with
    partial telemetry, and the engine is free (and correct) for the next
    run."""
    topo = build_topology("line", 2048)
    cfg = _slow_cfg(telemetry=True)
    run(topo, cfg)  # warm (compile)
    t0 = time.monotonic()
    ctrl = run(topo, cfg)
    t_warm = time.monotonic() - t0
    assert ctrl.outcome == "converged"
    budget = max(0.05, t_warm / 4)
    t0 = time.monotonic()
    res = run(topo, cfg, deadline=time.monotonic() + budget)
    elapsed = time.monotonic() - t0
    assert res.outcome == "deadline_exceeded"
    assert res.converged is False
    assert 0 < res.rounds < ctrl.rounds
    # Partial telemetry: one row per executed round, nothing more.
    assert res.telemetry.data.shape[0] == res.rounds
    # deadline + one chunk + eps — the warm full run is several times the
    # budget, so overshooting it would fail this bound.
    assert elapsed < budget + 0.75 * t_warm, (elapsed, budget, t_warm)
    # The engine is free and untainted: the next run is the control.
    again = run(topo, cfg)
    assert (again.rounds, again.outcome) == (ctrl.rounds, "converged")


def test_deadline_far_future_is_neutral():
    topo = build_topology("line", 512)
    cfg = _slow_cfg(n=512)
    ctrl = run(topo, cfg)
    res = run(topo, cfg, deadline=time.monotonic() + 3600.0)
    assert (res.rounds, res.outcome, res.converged_count) == (
        ctrl.rounds, ctrl.outcome, ctrl.converged_count
    )


def test_run_record_schema_v5_and_outcome_vocabulary():
    from cop5615_gossip_protocol_tpu.utils import metrics as metrics_mod

    assert RUN_RECORD_SCHEMA_VERSION == 5
    topo = build_topology("line", 512)
    cfg = _slow_cfg(n=512)
    run(topo, cfg)  # warm
    res = run(topo, cfg, deadline=time.monotonic())  # expires immediately
    rec = metrics_mod.run_record(cfg, topo, res)
    assert rec["schema_version"] == 5
    assert rec["outcome"] == "deadline_exceeded"


def test_cli_deadline_ms(tmp_path):
    from cop5615_gossip_protocol_tpu.cli import main

    out = tmp_path / "run.jsonl"
    rc = main([
        "2048", "line", "gossip", "--platform", "cpu", "--quiet",
        "--chunk-rounds", "8", "--max-rounds", "6000",
        "--deadline-ms", "1", "--jsonl", str(out),
    ])
    assert rc == 1  # not converged
    rec = json.loads(out.read_text().splitlines()[-1])
    assert rec["outcome"] == "deadline_exceeded"
    assert rec["schema_version"] == RUN_RECORD_SCHEMA_VERSION
    assert main(["64", "full", "gossip", "--platform", "cpu", "--quiet",
                 "--deadline-ms", "0"]) == 2
    assert main(["64", "full", "gossip", "--platform", "cpu", "--quiet",
                 "--replicas", "2", "--deadline-ms", "100"]) == 2


def test_sweep_deadline_marks_unconverged_lanes():
    topo = build_topology("line", 2048)
    cfg = _slow_cfg()
    sres = run_batched_keys(topo, cfg, [0, 1], lanes=2,
                            deadline=time.monotonic())
    assert sres.cancelled is True
    assert all(o == "deadline_exceeded" for o in sres.outcome)
    assert all(0 < r < cfg.max_rounds for r in sres.rounds)


# ----------------------------------------------------- quarantine breaker


def test_quarantine_circuit_state_machine():
    q = pool_mod.Quarantine(cooldown_s=10.0, registry=obs.Registry())
    assert q.check("k", now=0.0) == "closed"
    q.trip("k", now=0.0)
    assert q.check("k", now=5.0) == "open"
    # Cooldown expired: exactly one probe is handed out.
    assert q.check("k", now=11.0) == "probe"
    assert q.check("k", now=11.0) == "open"
    assert q.state("k") == "half-open"
    # Failed probe re-opens for another cooldown.
    q.record("k", ok=False, now=12.0)
    assert q.check("k", now=15.0) == "open"
    assert q.check("k", now=23.0) == "probe"
    q.record("k", ok=True)
    assert q.check("k") == "closed"
    assert q.open_count() == 0


def test_pool_invalidate_drops_matching_entries():
    p = pool_mod.WarmEnginePool(capacity=8, registry=obs.Registry())
    p.get_or_build(("batch-engine", "canonA", 4), lambda: "A")
    p.get_or_build(("batch-engine", "canonB", 4), lambda: "B")
    p.get_or_build(("run-chunk", "canonA", True), lambda: "C")
    dropped = p.invalidate(lambda k: k[1] == "canonA")
    assert dropped == 2 and len(p) == 1
    assert p.stats()["invalidations"] == 2
    # A rebuilt entry is a fresh miss.
    eng, hit = p.get_or_build(("batch-engine", "canonA", 4), lambda: "A2")
    assert (eng, hit) == ("A2", False)


# ------------------------------------------- priorities, shedding, 429s


def _cfg32(seed=0, **kw):
    return SimConfig(n=32, topology="full", algorithm="gossip", seed=seed,
                     engine="chunked", **kw)


def test_priority_queues_bounded_per_class_with_retry_after():
    stats = ServingStats()
    b = MicroBatcher(stats=stats, queue_limit=2, min_lanes=1)
    # NOT started: submissions stay queued, so the bounds are observable.
    b.submit(_cfg32(0), False, priority="interactive")
    b.submit(_cfg32(1), False, priority="interactive")
    # A different class has its own headroom.
    b.submit(_cfg32(2), False, priority="best_effort")
    with pytest.raises(AdmissionError) as e:
        b.submit(_cfg32(3), False, priority="interactive")
    assert e.value.priority == "interactive"
    assert e.value.queue_depth == 2 and e.value.queue_limit == 2
    assert e.value.retry_after_s >= 1.0
    assert b.queue_depth() == 3
    assert b.class_depth("interactive") == 2
    b.stop(drain=False)
    assert stats.failed == 3  # every queued request got shutting_down


def test_submit_rejects_unknown_priority():
    b = MicroBatcher(stats=ServingStats(), min_lanes=1)
    with pytest.raises(ValueError, match="priority"):
        b.submit(_cfg32(0), False, priority="urgent")
    b.stop(drain=False)


def test_overload_sheds_lowest_class_first():
    """The ISSUE 8 overload pin (unit form): with interactive's SLO in
    breach, queued best_effort/batch requests are shed with structured
    Retry-After bodies while interactive work executes."""
    stats = ServingStats()
    b = MicroBatcher(
        stats=stats, min_lanes=1, window_s=0.001,
        slo_s={"interactive": 1e-4, "batch": 60.0, "best_effort": 60.0},
    )
    ri = b.submit(_cfg32(1), False, priority="interactive")
    rb = b.submit(_cfg32(2), False, priority="batch")
    re_ = b.submit(_cfg32(3), False, priority="best_effort")
    time.sleep(0.02)  # interactive's wave wait is now over its (tiny) SLO
    b.start()
    for r in (ri, rb, re_):
        assert r.ready.wait(120)
    assert ri.status == 200 and ri.response["result"]["outcome"] == "converged"
    for r in (rb, re_):
        assert r.status == 503, r.response
        assert r.response["error"] == "shed"
        assert r.response["retry_after_s"] >= 1.0
        assert any(e["event"] == "request-shed" for e in r.response["events"])
    snap = stats.snapshot()
    assert snap["shed"] == 2 and snap["completed"] == 1
    assert snap["class_queue_wait_ms_p99"]["interactive"] is not None
    b.stop()


def test_deadline_expired_in_queue_sheds_before_dispatch():
    stats = ServingStats()
    b = MicroBatcher(stats=stats, min_lanes=1)
    r = b.submit(_cfg32(0), False, deadline_ms=1.0)
    time.sleep(0.05)
    b.start()
    assert r.ready.wait(30)
    assert r.status == 504
    assert r.response["error"] == "deadline_exceeded"
    snap = stats.snapshot()
    assert snap["shed"] == 1 and snap["deadline_exceeded"] == 1
    assert snap["batched_requests"] == 0  # never dispatched
    b.stop()


def test_serving_deadline_in_flight_partial_result():
    """In-flight cancellation through the serving stack: the engine stops
    at the next retired chunk and the 200 carries
    outcome=deadline_exceeded with partial telemetry."""
    app = ServingApp(window_s=0.005, max_lanes=4, min_lanes=1)
    try:
        status, resp = app.handle_run({
            "schema_version": 2, "n": 2048, "topology": "line",
            "algorithm": "gossip", "seed": 0, "telemetry": True,
            "deadline_ms": 300,
            "params": {"chunk_rounds": 8, "max_rounds": 6000},
        })
        assert status == 200, resp
        assert resp["result"]["outcome"] == "deadline_exceeded"
        assert resp["result"]["converged"] is False
        assert len(resp["telemetry"]) == resp["result"]["rounds"] > 0
        snap = app.snapshot()
        assert snap["completed"] == 1
        assert snap["deadline_exceeded"] == 1 and snap["shed"] == 0
    finally:
        app.close()


# -------------------------------------- stuck executor -> quarantine cycle


def test_stuck_executor_failover_quarantine_halfopen_recovery(
    monkeypatch, tmp_path
):
    """The tentpole integration pin: a wedged dispatch fails over to a
    fresh executor (the wedged request still gets a 200 via the one-shot
    detour), the bucket's circuit opens, and the half-open probe recovers
    it — the full cycle visible in the event log, identities exact."""
    monkeypatch.setenv("GOSSIP_TPU_STRICT_ENGINE", "0")
    from cop5615_gossip_protocol_tpu.utils.events import (
        RunEventLog,
        read_events,
    )

    ev_path = tmp_path / "events.jsonl"
    app = ServingApp(
        window_s=0.005, max_lanes=8, min_lanes=1,
        stuck_min_s=1.0, stuck_mult=0.0, quarantine_s=4.0,
        event_log=RunEventLog(ev_path),
    )
    body = {"schema_version": 2, "n": 32, "topology": "full",
            "algorithm": "gossip"}
    try:
        # Warm the batched engine AND the one-shot engine (the failover
        # detour) so budgets clock engine time, not compiles.
        st, _ = app.handle_run(dict(body, seed=1))
        assert st == 200
        run(build_topology("full", 32), _cfg32(1))

        # The continuous executor (ISSUE 14, default) dispatches through
        # serve_lanes; the half-open probe deliberately rides the wave
        # path (run_batched_keys), so the recovery request below runs the
        # REAL engine while the wedge hits the continuous dispatch.
        real = sweep_mod.serve_lanes
        state = {"wedge": 1}

        def flaky(*a, **k):
            if state["wedge"] > 0:
                state["wedge"] -= 1
                time.sleep(4.0)  # > the 1.0s budget: a wedge
            return real(*a, **k)

        monkeypatch.setattr(sweep_mod, "serve_lanes", flaky)

        t0 = time.monotonic()
        st, resp = app.handle_run(dict(body, seed=3))
        elapsed = time.monotonic() - t0
        # Failed over and answered BEFORE the wedge would have returned.
        assert st == 200 and resp["result"]["outcome"] == "converged"
        assert elapsed < 3.5, elapsed
        assert "quarantined" in str(resp["serving"]["engine_degraded"])

        # While the circuit is open, the bucket serves via one-shot.
        st2, resp2 = app.handle_run(dict(body, seed=4))
        assert st2 == 200
        assert "quarantined" in str(resp2["serving"]["engine_degraded"])

        # Cooldown expires -> the next request is the half-open probe.
        time.sleep(4.2)
        st3, resp3 = app.handle_run(dict(body, seed=5))
        assert st3 == 200 and resp3["serving"]["engine_degraded"] is None

        snap = app.snapshot()
        kinds = [e["event"] for e in read_events(ev_path)]
        cycle = [k for k in kinds if "quarant" in k or k == "executor-stuck"]
        assert cycle == [
            "executor-stuck", "engine-quarantined",
            "quarantine-half-open", "quarantine-recovered",
        ], cycle
        assert snap["received"] == (
            snap["completed"] + snap["failed"] + snap["rejected"]
            + snap["invalid"] + snap["timed_out"] + snap["shed"]
        ), snap
        assert snap["batched_requests"] == (
            snap["completed"] + snap["failed"] + snap["timed_out_dispatched"]
        ), snap
        assert snap["failed"] == 0
    finally:
        app.close()


# -------------------------------------------------------- shutdown edges


def test_stop_nodrain_resolves_in_flight_with_shutting_down(monkeypatch):
    """ISSUE 8 satellite: stop(drain=False) must resolve queued AND
    in-flight requests with a structured shutting_down error — today's
    client never hangs until the front timeout."""
    stats = ServingStats()
    b = MicroBatcher(stats=stats, min_lanes=1, window_s=0.001)

    real = sweep_mod.serve_lanes

    def wedged(*a, **k):
        time.sleep(3.0)
        return real(*a, **k)

    monkeypatch.setattr(sweep_mod, "serve_lanes", wedged)
    b.start()
    r = b.submit(_cfg32(0), False)
    deadline = time.monotonic() + 5
    while not r.is_dispatched() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert r.is_dispatched()
    t0 = time.monotonic()
    b.stop(drain=False)
    assert r.ready.wait(2.0)
    assert time.monotonic() - t0 < 2.0  # did NOT wait out the wedge
    assert r.status == 503 and r.response["error"] == "shutting_down"
    snap = stats.snapshot()
    assert snap["failed"] == 1
    assert snap["batched_requests"] == (
        snap["completed"] + snap["failed"] + snap["timed_out_dispatched"]
    ), snap


def test_drain_window_expiry_resolves_leftovers(monkeypatch):
    stats = ServingStats()
    b = MicroBatcher(stats=stats, min_lanes=1, window_s=0.001)

    real = sweep_mod.serve_lanes

    def wedged(*a, **k):
        time.sleep(5.0)
        return real(*a, **k)

    monkeypatch.setattr(sweep_mod, "serve_lanes", wedged)
    b.start()
    r = b.submit(_cfg32(0), False)
    deadline = time.monotonic() + 5
    while not r.is_dispatched() and time.monotonic() < deadline:
        time.sleep(0.01)
    t0 = time.monotonic()
    b.stop(drain=True, drain_window_s=0.4)
    elapsed = time.monotonic() - t0
    assert r.ready.wait(1.0)
    assert 0.3 < elapsed < 3.0, elapsed  # bounded by the window
    assert r.status == 503 and r.response["error"] == "shutting_down"


# ------------------------------------------------ orphaned-timeout hole


def test_front_timeout_claims_never_counts_completed(monkeypatch):
    """The PR 6 accounting hole, closed: a request whose front thread
    times out is CLAIMED — the executor's late completion is dropped, the
    request lands in timed_out (not completed), and every identity stays
    exact. The executor survives to serve the next request."""
    real = sweep_mod.serve_lanes
    state = {"slow": 1}

    def slow_once(*a, **k):
        # Sleep BEFORE the engine runs: under continuous batching the
        # source resolves each lane at its retiring boundary, so a sleep
        # after the real call would land after the response was already
        # released.
        if state["slow"] > 0:
            state["slow"] -= 1
            time.sleep(1.0)
        return real(*a, **k)

    app = ServingApp(window_s=0.005, max_lanes=4, min_lanes=1)
    try:
        # Warm first so the slow path's sleep dominates, not the compile
        # — under a generous timeout: a preceding test's failover can
        # leave this bucket's engine wave-built (refill program cold),
        # and the first continuous acquisition then pre-warms it, which
        # must not race the aggressive timeout the MEASURED request gets.
        st, warm_resp = app.handle_run({"schema_version": 1, "n": 32,
                                        "topology": "full",
                                        "algorithm": "gossip", "seed": 1})
        assert st == 200, warm_resp
        app.request_timeout_s = 0.25
        monkeypatch.setattr(sweep_mod, "serve_lanes", slow_once)
        t0 = time.monotonic()
        st, resp = app.handle_run({"schema_version": 1, "n": 32,
                                   "topology": "full",
                                   "algorithm": "gossip", "seed": 2})
        assert st == 503 and resp["error"] == "timeout"
        assert time.monotonic() - t0 < 0.9  # front released at timeout
        time.sleep(1.2)  # let the executor finish (and drop) the orphan
        snap = app.snapshot()
        assert snap["timed_out"] == 1
        assert snap["timed_out_dispatched"] == 1
        assert snap["completed"] == 1  # the warm request only
        assert snap["received"] == (
            snap["completed"] + snap["failed"] + snap["rejected"]
            + snap["invalid"] + snap["timed_out"] + snap["shed"]
        ), snap
        assert snap["batched_requests"] == (
            snap["completed"] + snap["failed"]
            + snap["timed_out_dispatched"]
        ), snap
        # Executor alive: next request completes normally.
        st, resp = app.handle_run({"schema_version": 1, "n": 32,
                                   "topology": "full",
                                   "algorithm": "gossip", "seed": 3})
        assert st == 200 and resp["result"]["outcome"] == "converged"
    finally:
        app.close()


# ---------------------------------------------------------- schema pins


def test_event_schema_v7():
    # v6: the fleet front's lifecycle events (front-request-rerouted /
    # front-request-completed) joined the vocabulary (ISSUE 18).
    # v7: the durable-state plane's checkpoint-corrupt-quarantined /
    # checkpoint-failed events + the enriched checkpoint-written
    # (generation/bytes/write_s) joined it (ISSUE 19).
    assert EVENT_SCHEMA_VERSION == 7


def test_healthz_lame_duck_and_drain_rejections():
    app = ServingApp(window_s=0.005, max_lanes=4, min_lanes=1)
    try:
        app.draining = True
        st, resp = app.handle_run({"schema_version": 1, "n": 32,
                                   "topology": "full",
                                   "algorithm": "gossip", "seed": 0})
        assert st == 503 and resp["error"] == "shutting_down"
        snap = app.snapshot()
        assert snap["rejected"] == 1 and snap["received"] == 1
    finally:
        app.draining = False
        app.close()
