"""Test harness bootstrap.

The reference (program.fs) has no tests at all — its validation story is manual
timed runs (SURVEY.md §4). This suite is the capability scaffolding the new
framework adds. Multi-device code paths are exercised without a TPU pod by
forcing 8 virtual CPU devices, per the distributed-without-a-cluster strategy
in SURVEY.md §4: the same `shard_map` collective program runs unchanged on CPU
devices.

This file MUST set the environment before jax is imported anywhere.
"""

import os
import sys
from pathlib import Path

# Repo root importable (package is not pip-installed in this environment).
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# Fail fast on engine errors during tests: the graceful-degradation ladder
# (models/runner.run) would otherwise mask real engine bugs by silently
# falling back to the chunked single-device path. Ladder tests monkeypatch
# this to "0" explicitly. scripts/tier1.sh exports the same default, so a
# bare `pytest tests/` matches CI.
os.environ.setdefault("GOSSIP_TPU_STRICT_ENGINE", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Force CPU-only AFTER importing jax: this container's sitecustomize
# registers a remote-TPU PJRT plugin and force-overrides jax_platforms at
# registration time, so the env var alone is not sufficient — a config
# update after import is. Without this, every pytest process claims the
# single remote TPU session and concurrent runs deadlock on the tunnel.
jax.config.update("jax_platforms", "cpu")

# float64 is required to honor the reference's delta = 1e-10 push-sum
# termination threshold (program.fs:187 et al.); on TPU the framework instead
# rescales delta for float32 (see SimConfig.resolved_delta). Tests run on CPU
# where x64 is native.
jax.config.update("jax_enable_x64", True)

# The cross-engine stream contract is defined over the partitionable
# threefry (default on current JAX, off on older runtimes) — opt in
# explicitly so golden trajectories and fused-vs-chunked bitwise pins hold
# on either (utils/compat.py).
jax.config.update("jax_threefry_partitionable", True)
