"""HBM-streaming pool engine (ops/fused_pool2.py), interpret mode on CPU.

The engine serves the implicit full topology past the VMEM-resident
engine's 2^21-node cap; tests force it at small populations by shrinking
ops/fused_pool.MAX_POOL_NODES (the runner reads it at dispatch time).
Oracles mirror tests/test_fused_pool.py: gossip bitwise vs the chunked XLA
pool path — on both the Z=0 (aligned population, single-window) and Z>0
(mod-n blend) code paths — push-sum on rounds/estimates, resume, gating.
"""

import jax
import jax.numpy as jnp
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import fused_pool, fused_pool2

# Interpret-mode Pallas oracle: bitwise engine validation that cannot
# fit the ROADMAP tier-1 wall-clock budget on a CPU-only container (the
# kernels run under the Pallas interpreter). Full-suite / TPU runs
# execute it: `pytest tests/` (no -m filter) or `pytest -m slow`.
pytestmark = pytest.mark.slow


def _cfg(n, algorithm="gossip", engine="fused", **kw):
    kw.setdefault("max_rounds", 5000)
    kw.setdefault("chunk_rounds", 16)
    return SimConfig(n=n, topology="full", algorithm=algorithm,
                     delivery="pool", engine=engine, **kw)


@pytest.fixture
def force_pool2(monkeypatch):
    # Shrink the VMEM engine's domain so dispatch routes to pool2.
    monkeypatch.setattr(fused_pool, "MAX_POOL_NODES", 1000)


@pytest.mark.parametrize("n", [20000,   # Z > 0: mod-n blend path
                               65536])  # Z = 0: single-window path
def test_pool2_gossip_matches_chunked_bitwise(n, force_pool2):
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology("full", n), _cfg(n, engine=engine))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.converged and b.converged
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


def test_pool2_gossip_suppression_bitwise(force_pool2):
    n = 20000
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology("full", n),
                _cfg(n, engine=engine, suppress_converged=True))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


def test_pool2_pushsum_matches_chunked(force_pool2):
    n = 20000
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology("full", n),
                _cfg(n, algorithm="push-sum", engine=engine, chunk_rounds=64))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.converged and b.converged
    assert a.rounds == b.rounds
    assert abs(a.estimate_mae - b.estimate_mae) < 1e-3


def test_pool2_drop_crash_matches_chunked_bitwise(force_pool2):
    # Failure model in the HBM-streaming tier: the drop gate is
    # regenerated at window grain, the crash plane streams alongside the
    # state windows (ops/fused_pool2.py). Integer gossip state — rounds +
    # converged-count equality is bitwise trajectory equality, and quorum
    # (not the legacy full count) ends the run.
    n = 20000
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology("full", n),
                _cfg(n, engine=engine, fault_rate=0.2,
                     crash_schedule="4:2000", quorum=0.95))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count
    assert a.outcome == b.outcome == "converged"
    assert a.converged_count < n


def test_pool2_resume_midway(force_pool2):
    n = 20000
    cfg = _cfg(n, chunk_rounds=8)
    topo = build_topology("full", n)
    snaps = []
    full = run(topo, cfg, on_chunk=lambda r, s: snaps.append((r, s)))
    assert len(snaps) >= 2
    r0, s0 = snaps[0]
    resumed = run(topo, cfg, start_state=jax.tree.map(jnp.asarray, s0),
                  start_round=r0)
    assert resumed.rounds == full.rounds
    assert resumed.converged_count == full.converged_count
    # A checkpoint taken at/after convergence must execute ZERO rounds.
    r_last, s_last = snaps[-1]
    again = run(topo, cfg, start_state=jax.tree.map(jnp.asarray, s_last),
                start_round=r_last)
    assert again.rounds == r_last


def test_pool2_chunk_rounds_not_multiple_of_8(force_pool2):
    n = 20000
    a = run(build_topology("full", n), _cfg(n, engine="chunked"))
    b = run(build_topology("full", n), _cfg(n, chunk_rounds=5))
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


def test_pool2_support_gating():
    cfg = _cfg(70000)
    topo = build_topology("full", 70000)
    assert fused_pool2.pool2_support(topo, cfg) is None
    line = build_topology("line", 100)
    assert "full topology" in fused_pool2.pool2_support(line, cfg)
    over = build_topology("full", fused_pool2.MAX_POOL2_NODES + 1)
    assert "HBM-plane budget" in fused_pool2.pool2_support(over, cfg)


def test_dispatch_routes_pool2_past_vmem_cap(monkeypatch, force_pool2):
    from cop5615_gossip_protocol_tpu.models import runner as runner_mod

    seen = {}
    real = runner_mod._run_fused

    def spy(topo, cfg, key, on_chunk, start_state, start_round, interpret,
            variant="stencil", **kw):
        # **kw forwards the dispatch's newer kwargs (on_telemetry, t_enter,
        # deadline, probe) — the spy only records the resolved tier.
        seen["variant"] = variant
        return real(topo, cfg, key, on_chunk, start_state, start_round,
                    interpret, variant=variant, **kw)

    monkeypatch.setattr(runner_mod, "_run_fused", spy)
    r = run(build_topology("full", 20000), _cfg(20000))
    assert r.converged
    assert seen == {"variant": "pool2"}
