"""Stencil (shift-based) delivery — the scatter-free fast path for
offset-structured topologies (ops/topology.stencil_offsets,
ops/delivery.deliver_stencil).

Oracle: the general scatter-add `deliver`. Gossip counts are int32, so the
two paths must agree bitwise; push-sum floats may differ only by summation
order (offsets order vs sort order), so those compare with tight tolerances.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import delivery, sampling
from cop5615_gossip_protocol_tpu.ops import topology as T

STENCIL_KINDS = ["line", "ring", "grid2d", "ref2d", "grid3d", "torus3d"]


@pytest.mark.parametrize("kind", STENCIL_KINDS)
def test_offsets_detected(kind):
    topo = build_topology(kind, 64)
    offs = T.stencil_offsets(topo)
    assert offs is not None
    # Every live adjacency slot's displacement is covered.
    cols = np.arange(topo.max_deg)[None, :]
    live = cols < topo.degree[:, None]
    ids = np.arange(topo.n)[:, None]
    diffs = np.unique((topo.neighbors.astype(np.int64) - ids)[live] % topo.n)
    assert set(diffs) == set(int(d) for d in offs)


def test_offsets_expected_sets():
    line = T.stencil_offsets(build_topology("line", 100))
    assert set(int(d) for d in line) == {1, 99}
    g2 = build_topology("grid2d", 100)  # 10x10
    offs = T.stencil_offsets(g2)
    assert set(int(d) for d in offs) == {1, 10, 90, 99}


@pytest.mark.parametrize("kind", ["full", "imp3d", "imp2d"])
def test_offsets_absent_for_unstructured(kind):
    topo = build_topology(kind, 512, seed=3)
    assert T.stencil_offsets(topo) is None


def test_offsets_reference_mode_quirks():
    # Q1 extra actor (degree 0) must not break detection; ref2d is line-wired.
    for kind in ["line", "ref2d", "grid2d", "grid3d"]:
        topo = build_topology(kind, 30, semantics="reference")
        assert T.stencil_offsets(topo) is not None, kind


@pytest.mark.parametrize("kind", STENCIL_KINDS)
@pytest.mark.parametrize("dtype", [jnp.int32, jnp.float64])
def test_stencil_equals_scatter_one_round(kind, dtype):
    topo = build_topology(kind, 81)
    offs = T.stencil_offsets(topo)
    key = jax.random.PRNGKey(7)
    bits = sampling.uniform_bits(key, topo.n)
    targets = sampling.targets_explicit(
        bits, jnp.asarray(topo.neighbors), jnp.asarray(topo.degree)
    )
    vals = jax.random.uniform(key, (topo.n,), jnp.float64)
    if dtype == jnp.int32:
        vals = (vals * 10).astype(jnp.int32)
    else:
        vals = vals.astype(dtype)
    # Degree-0 nodes (reference-mode orphans) must not send.
    vals = jnp.where(jnp.asarray(topo.degree) > 0, vals, 0)
    want = delivery.deliver(vals, targets, topo.n)
    got = delivery.deliver_stencil(vals, targets, offs, topo.n)
    if dtype == jnp.int32:
        assert (np.asarray(want) == np.asarray(got)).all()
    else:
        np.testing.assert_allclose(np.asarray(want), np.asarray(got), rtol=1e-12)


@pytest.mark.parametrize("kind", ["line", "torus3d"])
def test_full_run_trajectory_matches_scatter_gossip(kind):
    # Gossip state is integer — identical targets + exact delivery means the
    # two delivery strategies must produce the same trajectory bitwise.
    results = {}
    for mode in ["scatter", "stencil"]:
        cfg = SimConfig(n=64, topology=kind, algorithm="gossip",
                        delivery=mode, max_rounds=5000, chunk_rounds=64)
        results[mode] = run(build_topology(kind, 64), cfg)
    a, b = results["scatter"], results["stencil"]
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count
    assert a.converged and b.converged


def test_full_run_trajectory_matches_scatter_pushsum():
    kind = "grid2d"
    results = {}
    for mode in ["scatter", "stencil"]:
        cfg = SimConfig(n=49, topology=kind, algorithm="push-sum", dtype="float64",
                        delivery=mode, max_rounds=20000, chunk_rounds=128)
        results[mode] = run(build_topology(kind, 49), cfg)
    a, b = results["scatter"], results["stencil"]
    assert a.converged and b.converged
    # Float summation order differs; rounds-to-converge should still agree at
    # f64 on this scale, and the estimates must both be near-exact.
    assert a.rounds == b.rounds
    assert a.estimate_mae < 1e-6 and b.estimate_mae < 1e-6


def test_stencil_on_unstructured_topology_raises():
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", delivery="stencil")
    with pytest.raises(ValueError, match="stencil"):
        run(build_topology("full", 64), cfg)


def test_stencil_sharded_and_walk_paths():
    # Sharded stencil is now served by the halo-exchange plan
    # (parallel/halo.py) — explicit delivery='stencil' under n_devices>1
    # runs and matches the single-device trajectory.
    topo = build_topology("line", 64)
    cfg = SimConfig(n=64, topology="line", algorithm="gossip",
                    delivery="stencil", n_devices=2)
    r2 = run(topo, cfg)
    r1 = run(topo, SimConfig(n=64, topology="line", algorithm="gossip",
                             delivery="stencil"))
    assert r2.converged and r2.rounds == r1.rounds
    # The fail-loudly contract still holds on the single-walk early exit.
    topo_ref = build_topology("line", 16, semantics="reference")
    cfg = SimConfig(n=16, topology="line", algorithm="push-sum", dtype="float64",
                    semantics="reference", delivery="stencil", max_rounds=100)
    with pytest.raises(ValueError, match="single-walk"):
        run(topo_ref, cfg)


def test_bad_delivery_name_rejected():
    with pytest.raises(ValueError, match="delivery"):
        SimConfig(n=8, delivery="teleport")
