"""Replicated-pool2 composition (parallel/pool2_sharded.py).

The full topology — the O(N^2) wall — past one chip's HBM budget
(ISSUE 10): the pool2 zero-send-plane HBM pipeline per shard, ONE
all_gather of the compact windowed send summaries per round. The design
claim is BITWISE equality with the single-device pool2 engine
(ops/fused_pool2.py) at every device count, through every knob the plan
serves: gossip int state, push-sum float state to the last bit, drop +
crash + quorum, global termination, resume, overlap on/off.

Fast plan/gating/capability/ceiling pins run in tier-1; interpret-mode
kernel oracles carry the slow mark (the ROADMAP tier-1 wall budget).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import fused_pool, fused_pool2
from cop5615_gossip_protocol_tpu.parallel.pool2_sharded import (
    plan_pool2_sharded,
)

# Smallest sharded pool population: 512-row padded layout -> two 256-row
# shards.
N = 262_144


def _cfg(n, algorithm="gossip", **kw):
    kw.setdefault("delivery", "pool")
    kw.setdefault("engine", "fused")
    kw.setdefault("max_rounds", 400)
    if kw.get("n_devices"):
        kw.setdefault("chunk_rounds", 1)
    else:
        kw.setdefault("chunk_rounds", 16)
    return SimConfig(n=n, topology="full", algorithm=algorithm, **kw)


@pytest.fixture
def force_pool2(monkeypatch):
    # Collapse the VMEM pool cap so BOTH the single-device dispatch and
    # the sharded ladder route to the pool2 tier (the runner reads it at
    # dispatch time; the VMEM composition's plan reads it through
    # pool_common_support).
    monkeypatch.setattr(fused_pool, "MAX_POOL_NODES", 1000)


def _grab(final, tag):
    def f(rounds, state):
        final[tag] = state
    return f


# --- fast plan / gating / capability pins (tier-1) -------------------------


def test_plan_accepts_and_ceiling_past_2_28():
    # The ISSUE 10 acceptance row: the plan — a pure function of
    # (n, cfg, n_dev), so this is hardware-free — admits the full
    # topology at >= 2^28 aggregate nodes, past the single-device pool2
    # engine's 2^27 HBM cap, for both algorithms.
    for algorithm in ("push-sum", "gossip"):
        for n in (N, 1 << 28):
            plan = plan_pool2_sharded(
                build_topology("full", n),
                _cfg(n, algorithm=algorithm, n_devices=8), 8
            )
            assert not isinstance(plan, str), (algorithm, n, plan)
    # and refuses honestly where the summary planes themselves cannot fit
    big = 1 << 33
    for wire, marker in (
        ("reduce_scatter", "reduce_scatter wire"),
        ("all_gather", "gathered"),
    ):
        reason = plan_pool2_sharded(
            build_topology("full", big),
            _cfg(big, n_devices=8, pool2_wire=wire), 8,
        )
        assert isinstance(reason, str) and marker in reason


def test_plan_resolves_pool2_wire_by_mesh_width():
    # ISSUE 15: auto picks the banded reduce_scatter wire exactly when
    # the mesh is wider than the pool (each band then undercuts the full
    # gathered copy); explicit values force either wire, and the plan
    # returns the RESOLVED wire so dispatch and declaration (analysis/
    # wire_specs.wire_env) share one decision.
    topo = build_topology("full", N)
    assert plan_pool2_sharded(topo, _cfg(N, n_devices=2), 2)[3] == (
        "all_gather"
    )
    assert plan_pool2_sharded(topo, _cfg(N, n_devices=8), 8)[3] == (
        "reduce_scatter"
    )
    assert plan_pool2_sharded(
        topo, _cfg(N, n_devices=2, pool2_wire="reduce_scatter"), 2
    )[3] == "reduce_scatter"
    assert plan_pool2_sharded(
        topo, _cfg(N, n_devices=8, pool2_wire="all_gather"), 8
    )[3] == "all_gather"


def test_band_margin_and_starts_geometry():
    # The band geometry invariants the reduce_scatter kernel relies on:
    # margin covers the mirror rows (16) plus — at padded populations —
    # the 8-aligned slack between the d and d+Z window starts, and every
    # band start is 8-aligned in [0, R).
    from cop5615_gossip_protocol_tpu.ops.fused_pool import (
        build_pool_layout,
    )
    from cop5615_gossip_protocol_tpu.parallel.pool2_sharded import (
        band_margin,
        band_starts,
    )

    lay0 = build_pool_layout(N)  # Z == 0
    assert lay0.n_pad == N and band_margin(lay0) == 16
    layz = build_pool_layout(N - 1000)  # Z == 1000
    z = layz.n_pad - layz.n
    assert z == 1000
    assert band_margin(layz) == 16 + ((z // 128 + 8 + 7) // 8) * 8
    offs = jnp.asarray([1, 127, 128, layz.n - 1], jnp.int32)
    starts = np.asarray(band_starts(offs, layz))
    assert ((starts % 8) == 0).all()
    assert ((starts >= 0) & (starts < layz.rows)).all()


def test_plan_gating_reasons():
    cfg = _cfg(N, n_devices=2)
    topo = build_topology("full", N)
    assert "implicit full" in plan_pool2_sharded(
        build_topology("torus3d", 4096), cfg, 2
    )
    assert "delivery='pool'" in plan_pool2_sharded(
        topo, _cfg(N, delivery="auto", n_devices=2), 2
    )
    assert "dup/delay" in plan_pool2_sharded(
        topo, _cfg(N, n_devices=2, dup_rate=0.1), 2
    )
    assert "revive" in plan_pool2_sharded(
        topo, _cfg(N, n_devices=2, fault_rate=0.1, crash_schedule="4:999",
                   revive_rate=0.5), 2
    )
    assert "telemetry" in plan_pool2_sharded(
        topo, _cfg(N, n_devices=2, telemetry=True), 2
    )


def test_capability_messages_name_the_sharded_composition():
    # Capability-matrix honesty (ISSUE 10): the single-device pool2
    # support must point past its own caps to the sharded composition.
    topo = build_topology("full", N)
    msg = fused_pool2.pool2_support(topo, _cfg(N, n_devices=2))
    assert "single-device" in msg and "pool2_sharded" in msg
    big = build_topology("full", fused_pool2.MAX_POOL2_NODES + 512 * 128)
    msg = fused_pool2.pool2_support(big, _cfg(big.n))
    assert "HBM-plane budget" in msg and "pool2_sharded" in msg


def test_runner_ladder_demotes_vmem_to_pool2_and_refuses_loudly(
    force_pool2,
):
    # The runner's implicit-full fused dispatch tiers the compositions:
    # VMEM composition while the population fits its kernel cap,
    # replicated-pool2 past it. With the cap collapsed the dispatch must
    # land here (pinned by the slow oracles running through `run`), and
    # a config NEITHER serves must raise ONE ValueError naming both
    # refusals — not a bare traceback from the first.
    topo = build_topology("full", N)
    with pytest.raises(ValueError) as ei:
        run(topo, _cfg(N, n_devices=2, fault_rate=0.1,
                       crash_schedule="4:999", revive_rate=0.5))
    msg = str(ei.value)
    assert "VMEM pool composition" in msg
    assert "replicated-pool2 composition" in msg


# --- interpret-mode kernel oracles (slow suite) ----------------------------


@pytest.mark.slow
def test_gossip_bitwise_vs_single_device(force_pool2):
    topo = build_topology("full", N)
    r1 = run(topo, _cfg(N))
    for nd in (2, 4):
        for ov in (True, False):
            r2 = run(topo, _cfg(N, n_devices=nd, overlap_collectives=ov))
            assert (r2.rounds, r2.converged_count) == (
                r1.rounds, r1.converged_count
            ), (nd, ov)


@pytest.mark.slow
def test_pushsum_state_bitwise(force_pool2):
    topo = build_topology("full", N)
    final = {}
    r = run(topo, _cfg(N, algorithm="push-sum", max_rounds=48,
                       chunk_rounds=48),
            on_chunk=_grab(final, "single"))
    assert r.rounds == 48
    r = run(topo, _cfg(N, algorithm="push-sum", n_devices=2, max_rounds=48),
            on_chunk=_grab(final, "sh"))
    assert r.rounds == 48
    for f in ("s", "w", "term", "conv"):
        a = np.asarray(getattr(final["single"], f))[:N]
        b = np.asarray(getattr(final["sh"], f))[:N]
        assert (a != b).sum() == 0, f


@pytest.mark.slow
def test_drop_crash_quorum_matches_single_device(force_pool2):
    # Drop gates and the crash plane are REGENERATED per window inside
    # the kernel; the quorum need falls with the dead — converged-count
    # equality at the stop round is trajectory equality.
    topo = build_topology("full", N)
    kw = dict(fault_rate=0.2, crash_schedule="4:20000", quorum=0.95)
    r1 = run(topo, _cfg(N, **kw))
    r2 = run(topo, _cfg(N, n_devices=2, **kw))
    assert (r1.rounds, r1.converged_count) == (r2.rounds, r2.converged_count)


@pytest.mark.slow
def test_pushsum_global_termination_exact(force_pool2):
    topo = build_topology("full", N)
    r1 = run(topo, _cfg(N, algorithm="push-sum", termination="global",
                        delta=1e-1, max_rounds=500, chunk_rounds=16))
    r2 = run(topo, _cfg(N, algorithm="push-sum", termination="global",
                        delta=1e-1, max_rounds=500, n_devices=2))
    assert r1.rounds == r2.rounds
    assert r1.converged_count == r2.converged_count


@pytest.mark.slow
def test_reduce_scatter_wire_bitwise_vs_all_gather(force_pool2):
    # ISSUE 15 acceptance: the banded reduce_scatter wire is a pure
    # reorganization of who holds which summary rows — trajectories are
    # BITWISE the all_gather composition's on the interpret oracle at 2
    # AND 4 devices, both schedules. Gossip ints pin the stream exactly;
    # the run-level (rounds, converged_count) equality then pins the
    # whole trajectory (count monotonicity).
    topo = build_topology("full", N)
    ref = run(topo, _cfg(N, n_devices=2, pool2_wire="all_gather"))
    for nd in (2, 4):
        for ov in (True, False):
            r = run(topo, _cfg(N, n_devices=nd, overlap_collectives=ov,
                               pool2_wire="reduce_scatter"))
            assert (r.rounds, r.converged_count) == (
                ref.rounds, ref.converged_count
            ), (nd, ov)


@pytest.mark.slow
def test_reduce_scatter_wire_pushsum_state_bitwise(force_pool2):
    # Push-sum float state to the last bit across the two wires, at a
    # PADDED population (Z > 0) so the straddle/wrap window reads the
    # band's anchor variant — the subtlest band-geometry path.
    n = N - 1000
    topo = build_topology("full", n)
    final = {}
    for wire in ("all_gather", "reduce_scatter"):
        r = run(topo, _cfg(n, algorithm="push-sum", n_devices=4,
                           max_rounds=48, pool2_wire=wire),
                on_chunk=_grab(final, wire))
        assert r.rounds == 48
    for f in ("s", "w", "term", "conv"):
        a = np.asarray(getattr(final["all_gather"], f))[:n]
        b = np.asarray(getattr(final["reduce_scatter"], f))[:n]
        assert (a != b).sum() == 0, f


@pytest.mark.slow
def test_resume_midway(force_pool2):
    topo = build_topology("full", N)
    snap = {}

    def keep(rounds, state):
        snap.setdefault("s0", (rounds, state))

    full = run(topo, _cfg(N, n_devices=2), on_chunk=keep)
    rounds0, s0 = snap["s0"]
    assert 0 < rounds0 < full.rounds
    resumed = run(topo, _cfg(N, n_devices=2),
                  start_state=jax.tree.map(jnp.asarray, s0),
                  start_round=rounds0)
    assert resumed.rounds == full.rounds
    assert resumed.converged_count == full.converged_count
