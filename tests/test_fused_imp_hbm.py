"""HBM-streaming imp engine (ops/fused_imp_hbm.py), interpret mode.

Serves imp2d/imp3d under pooled long-range sampling past the VMEM imp
engine's plane budget; tests force it at small populations by shrinking
that budget. Oracles: the chunked imp-pool path (round/count equality for
gossip, trajectory state for push-sum), suppression, resume, global
termination, gating.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import fused_imp, fused_imp_hbm

# Interpret-mode Pallas oracle: bitwise engine validation that cannot
# fit the ROADMAP tier-1 wall-clock budget on a CPU-only container (the
# kernels run under the Pallas interpreter). Full-suite / TPU runs
# execute it: `pytest tests/` (no -m filter) or `pytest -m slow`.
pytestmark = pytest.mark.slow


@pytest.fixture
def force_hbm(monkeypatch):
    monkeypatch.setattr(fused_imp, "_VMEM_BUDGET", 1000)


def _cfg(n, kind="imp3d", algorithm="gossip", engine="fused", **kw):
    kw.setdefault("delivery", "pool")
    kw.setdefault("max_rounds", 20000)
    kw.setdefault("chunk_rounds", 16)
    return SimConfig(n=n, topology=kind, algorithm=algorithm,
                     engine=engine, **kw)


@pytest.mark.parametrize("kind,n", [("imp3d", 27_000), ("imp2d", 26_896)])
def test_imp_dirs_match_builder(kind, n):
    # The lattice direction predicates/displacements duplicate the
    # arithmetic in fused_stencil_hbm._lattice_params in scalar form; this
    # pins BOTH against the builder's adjacency so a change to one that
    # misses the other fails loudly (lattice columns come first, the
    # long-range extra edge is the builder's last column).
    topo = build_topology(kind, n)
    n = topo.n
    dirs, offs, L = fused_imp_hbm._imp_dirs(topo)
    idx = np.arange(n, dtype=np.int64)
    got = np.full((n, topo.max_deg - 1), -1, dtype=np.int64)
    live_count = np.zeros(n, dtype=np.int64)
    for fn, d in dirs:
        live = np.asarray(fn(idx))
        rows = np.nonzero(live)[0]
        got[rows, live_count[rows]] = d
        live_count += live
    assert (live_count == topo.degree - 1).all()  # + the extra edge
    want = np.where(
        np.arange(topo.max_deg - 1)[None, :] < (topo.degree - 1)[:, None],
        (topo.neighbors[:, :-1].astype(np.int64) - idx[:, None]) % n,
        -1,
    )
    assert (got == want).all(), kind
    assert sorted(d for _, d in dirs) == offs and L == len(offs)


@pytest.mark.parametrize("kind,n", [("imp3d", 125000),   # 50^3, Z > 0
                                    ("imp2d", 65536)])   # 256^2, Z = 0
def test_imp_hbm_gossip_matches_chunked(kind, n, force_hbm):
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology(kind, n),
                _cfg(n, kind, engine=engine, max_rounds=300))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.converged and b.converged
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


def test_imp_hbm_gossip_suppression(force_hbm):
    n = 125000
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology("imp3d", n),
                _cfg(n, engine=engine, suppress_converged=True,
                     max_rounds=300))
        results[engine] = r
    assert results["chunked"].rounds == results["fused"].rounds
    assert results["chunked"].converged_count == results["fused"].converged_count


def test_imp_hbm_pushsum_matches_chunked_fixed_rounds(force_hbm):
    n = 125000
    final = {}

    def grab(tag):
        def f(rounds, state):
            final[tag] = state
        return f

    for engine in ["chunked", "fused"]:
        r = run(build_topology("imp3d", n),
                _cfg(n, algorithm="push-sum", engine=engine,
                     max_rounds=64, chunk_rounds=64),
                on_chunk=grab(engine))
        assert r.rounds == 64
    a, b = final["chunked"], final["fused"]
    np.testing.assert_allclose(np.asarray(a.s), np.asarray(b.s)[:n],
                               rtol=2e-5, atol=1e-3)
    np.testing.assert_allclose(np.asarray(a.w), np.asarray(b.w)[:n],
                               rtol=2e-5, atol=1e-6)
    sm = float(np.asarray(b.s, np.float64)[:n].sum())
    true = n * (n - 1) / 2
    assert abs(sm - true) / true < 1e-5  # mass conserved


def test_imp_hbm_pushsum_global_termination(force_hbm):
    n = 125000
    topo = build_topology("imp3d", n)
    rs = {}
    for engine in ["chunked", "fused"]:
        rs[engine] = run(topo, _cfg(n, algorithm="push-sum", engine=engine,
                                    termination="global", max_rounds=5000))
    assert rs["fused"].converged
    assert rs["chunked"].rounds == rs["fused"].rounds
    assert rs["fused"].converged_count == n


def test_imp_hbm_resume_midway(force_hbm):
    n = 125000
    cfg = _cfg(n, chunk_rounds=16, max_rounds=300)
    topo = build_topology("imp3d", n)
    snaps = []
    full = run(topo, cfg, on_chunk=lambda r, s: snaps.append((r, s)))
    assert len(snaps) >= 2
    r0, s0 = snaps[0]
    resumed = run(topo, cfg, start_state=jax.tree.map(jnp.asarray, s0),
                  start_round=r0)
    assert resumed.rounds == full.rounds
    assert resumed.converged_count == full.converged_count


def test_imp_hbm_support_gating():
    cfg = _cfg(125000)
    assert fused_imp_hbm.imp_hbm_support(
        build_topology("imp3d", 125000), cfg
    ) is None
    assert "imp" in fused_imp_hbm.imp_hbm_support(
        build_topology("torus3d", 4096), cfg
    )
    assert "single-device" in fused_imp_hbm.imp_hbm_support(
        build_topology("imp3d", 125000), _cfg(125000, n_devices=4)
    )
    assert "static extra edge" in fused_imp_hbm.imp_hbm_support(
        build_topology("imp3d", 1000, semantics="reference"),
        _cfg(1000, semantics="reference"),
    )


def test_dispatch_routes_imp_hbm_past_vmem_budget(monkeypatch, force_hbm):
    from cop5615_gossip_protocol_tpu.models import runner as runner_mod

    seen = {}
    real = runner_mod._run_fused

    def spy(*a, **kw):
        seen["variant"] = kw.get("variant")
        return real(*a, **kw)

    monkeypatch.setattr(runner_mod, "_run_fused", spy)
    n = 125000
    r = run(build_topology("imp3d", n), _cfg(n, max_rounds=100))
    assert seen["variant"] == "imp_hbm"
    assert r.rounds > 0
