"""Convergence-quality evidence for the bfloat16 dtype option.

bfloat16 has an 8-bit mantissa (~2-3 decimal digits), so the push-sum ratio
s/w near the true mean (n-1)/2 has an ulp far coarser than float32 — the
1e-2 default delta (SimConfig.resolved_delta) is what makes termination
meaningful at that resolution. These tests pin what that policy delivers:

- on expander-like topologies (full, torus3d) the estimate lands within
  0.5% / 1% relative of the true mean — bf16 is a legitimate fast mode there;
- on slow-mixing topologies (grid2d) coarse rounding makes the ratio look
  stable before mixing completes, degrading the estimate to the few-percent
  range — converges, but documented as degraded.

Measured (CPU, seeds 0-2): full n=1024 rel MAE 0.06-0.12%, torus3d n=512
0.17-0.35%, grid2d n=400 2.4-4.1%.
"""

import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run


def _rel_mae(topo_kind: str, n: int, seed: int) -> tuple[float, object]:
    cfg = SimConfig(
        n=n, topology=topo_kind, algorithm="push-sum", dtype="bfloat16",
        seed=seed, engine="chunked",
    )
    topo = build_topology(topo_kind, n)
    result = run(topo, cfg)
    assert result.converged, f"{topo_kind} n={n} seed={seed} failed to converge"
    return result.estimate_mae / result.true_mean, result


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_bf16_full_estimate_quality(seed):
    rel, result = _rel_mae("full", 1024, seed)
    assert rel < 0.005, f"bf16 full estimate degraded: rel MAE {rel:.4%}"
    # Sanity: the 1e-2 delta doesn't stall the run (f32 converges in ~50
    # rounds here; bf16 should be in the same regime, not 10x).
    assert result.rounds < 200


@pytest.mark.parametrize("seed", [0, 1])
def test_bf16_torus3d_estimate_quality(seed):
    rel, _ = _rel_mae("torus3d", 512, seed)
    assert rel < 0.01, f"bf16 torus3d estimate degraded: rel MAE {rel:.4%}"


def test_bf16_grid2d_converges_but_degraded():
    """Slow-mixing topologies: bf16 ratio stability fires before mixing
    completes. Pin the documented degradation envelope so a silent regression
    (either direction) surfaces."""
    rel, _ = _rel_mae("grid2d", 400, seed=0)
    assert rel < 0.10  # converges with a usable estimate...
    assert rel > 0.005  # ...but measurably degraded vs expanders (documented)
