"""Convergence-quality evidence for the bfloat16 dtype option.

bfloat16 has an 8-bit mantissa (~2-3 decimal digits), so the push-sum ratio
s/w near the true mean (n-1)/2 has an ulp far coarser than float32 — the
1e-2 default delta (SimConfig.resolved_delta) is what makes termination
meaningful at that resolution. These tests pin what that policy delivers:

- on expander-like topologies (full, torus3d) the estimate lands within
  0.5% / 1% relative of the true mean — bf16 is a legitimate fast mode there;
- on slow-mixing topologies (grid2d) coarse rounding makes the ratio look
  stable before mixing completes, degrading the estimate to the few-percent
  range — converges, but documented as degraded;
- on 1-D chains (line/ring/ref2d) the latch fires ~O(n) rounds into an
  O(n^2) mixing process and the "estimate" is 39-49% off — SimConfig
  REJECTS those combinations at construction (fail-loudly contract).

Measured (CPU, seeds 0-2): full n=1024 rel MAE 0.06-0.12%, torus3d n=512
0.17-0.35%, grid3d n=512 0.39%, imp3d n=512 0.06%, imp2d n=400 0.48%,
grid2d n=400 2.4-4.1%; line/ring/ref2d n=256 38.8-48.8% (rejected).
"""

import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run


def _rel_mae(topo_kind: str, n: int, seed: int) -> tuple[float, object]:
    cfg = SimConfig(
        n=n, topology=topo_kind, algorithm="push-sum", dtype="bfloat16",
        seed=seed, engine="chunked",
    )
    topo = build_topology(topo_kind, n)
    result = run(topo, cfg)
    assert result.converged, f"{topo_kind} n={n} seed={seed} failed to converge"
    return result.estimate_mae / result.true_mean, result


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_bf16_full_estimate_quality(seed):
    rel, result = _rel_mae("full", 1024, seed)
    assert rel < 0.005, f"bf16 full estimate degraded: rel MAE {rel:.4%}"
    # Sanity: the 1e-2 delta doesn't stall the run (f32 converges in ~50
    # rounds here; bf16 should be in the same regime, not 10x).
    assert result.rounds < 200


@pytest.mark.parametrize("seed", [0, 1])
def test_bf16_torus3d_estimate_quality(seed):
    rel, _ = _rel_mae("torus3d", 512, seed)
    assert rel < 0.01, f"bf16 torus3d estimate degraded: rel MAE {rel:.4%}"


def test_bf16_grid2d_converges_but_degraded():
    """Slow-mixing topologies: bf16 ratio stability fires before mixing
    completes. Pin the documented degradation envelope so a silent regression
    (either direction) surfaces."""
    rel, _ = _rel_mae("grid2d", 400, seed=0)
    assert rel < 0.10  # converges with a usable estimate...
    assert rel > 0.005  # ...but measurably degraded vs expanders (documented)


@pytest.mark.parametrize("kind,n,bound", [
    ("grid3d", 512, 0.01), ("imp3d", 512, 0.01), ("imp2d", 400, 0.01),
])
def test_bf16_remaining_expander_class_quality(kind, n, bound):
    # VERDICT r3 #5: every dtype x topology combination is either pinned by
    # a test or rejected at config time. These three round out the
    # expander-class envelope.
    rel, _ = _rel_mae(kind, n, seed=0)
    assert rel < bound, f"bf16 {kind} estimate degraded: rel MAE {rel:.4%}"


@pytest.mark.parametrize("kind", ["line", "ring", "ref2d"])
def test_bf16_chain_topologies_rejected(kind):
    with pytest.raises(ValueError, match="40-49%"):
        SimConfig(n=256, topology=kind, algorithm="push-sum", dtype="bfloat16")
    # gossip carries integer state - dtype-insensitive, stays allowed.
    SimConfig(n=256, topology=kind, algorithm="gossip", dtype="bfloat16")
