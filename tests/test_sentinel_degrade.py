"""Health sentinels + graceful engine degradation (ISSUE 4 harness layers).

Pinned contracts:

- a non-finite state or a mass-divergence past --mass-tolerance surfaces
  as outcome="unhealthy" with the offending round — a structured outcome
  in RunResult/JSONL/events, never a traceback and never a wrong
  "converged" — on the chunked AND sharded engines;
- the sentinel is a Python-level flag: off traces the bitwise-identical
  program (trajectories match sentinel-on for healthy runs);
- fused tiers do not carry the sentinel: engine='auto' demotes to the
  chunked engine, engine='fused' rejects loudly;
- environmental engine failures walk the documented degradation ladder
  (fused->chunked, sharded->single-device) with transient-error retries,
  emitting structured engine-degraded events — unless strict mode
  (cfg.strict_engine / GOSSIP_TPU_STRICT_ENGINE) restores fail-fast;
- config-contract errors (ValueError) always fail fast, ladder or not.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models import pushsum as pushsum_mod
from cop5615_gossip_protocol_tpu.models import runner
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.utils import metrics


def _ps_state(n, corrupt=None):
    st = pushsum_mod.init_state(n, jnp.float32, 0)
    if corrupt == "nan":
        st = st._replace(s=st.s.at[3].set(jnp.nan))
    elif corrupt == "mass":
        st = st._replace(w=st.w.at[5].set(2.5))  # residual 1.5
    return st


# ------------------------------------------------------------- validation


def test_mass_tolerance_config_contracts():
    with pytest.raises(ValueError, match="push-sum"):
        SimConfig(n=64, topology="full", algorithm="gossip",
                  mass_tolerance=1e-3)
    with pytest.raises(ValueError, match="dup_rate"):
        SimConfig(n=64, topology="full", algorithm="push-sum",
                  mass_tolerance=1e-3, dup_rate=0.1)
    with pytest.raises(ValueError, match="fresh"):
        SimConfig(n=64, topology="full", algorithm="push-sum",
                  mass_tolerance=1e-3, crash_rate=0.01, revive_rate=0.1,
                  rejoin="fresh")
    with pytest.raises(ValueError, match="> 0"):
        SimConfig(n=64, topology="full", algorithm="push-sum",
                  mass_tolerance=0.0)


# --------------------------------------------------------------- sentinel


@pytest.mark.parametrize("corrupt,n_devices", [
    ("nan", None), ("mass", None), ("nan", 4), ("mass", 4),
])
def test_sentinel_trips_to_unhealthy_outcome(corrupt, n_devices):
    # A corrupt resume state (the smallest reproducible stand-in for
    # silent numerical corruption) must trip the sentinel on the FIRST
    # executed round — structured outcome, offending round, no traceback,
    # converged=False.
    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="push-sum",
                    mass_tolerance=1e-3, chunk_rounds=8,
                    n_devices=n_devices)
    r = run(topo, cfg, start_state=_ps_state(64, corrupt), start_round=5)
    assert r.outcome == "unhealthy"
    assert r.unhealthy_round == 5
    assert not r.converged
    rec = metrics.run_record(cfg, topo, r)
    assert rec["outcome"] == "unhealthy"
    assert rec["unhealthy_round"] == 5
    import json

    json.dumps(rec)  # JSONL-serializable even with a corrupt final state


def test_sentinel_healthy_run_matches_sentinel_off_bitwise():
    # Python-level flag: the sentinel must not perturb a healthy run's
    # trajectory or verdict.
    topo = build_topology("full", 128)
    base = dict(n=128, topology="full", algorithm="push-sum",
                chunk_rounds=16)
    r_off = run(topo, SimConfig(**base))
    r_on = run(topo, SimConfig(**base, mass_tolerance=1e-2))
    assert r_on.outcome == "converged"
    assert r_on.unhealthy_round is None
    assert (r_on.rounds, r_on.converged_count, r_on.estimate_mae) == (
        r_off.rounds, r_off.converged_count, r_off.estimate_mae
    )


def test_sentinel_tolerance_is_respected():
    # Residual 1.5 passes a loose tolerance, trips a tight one.
    topo = build_topology("full", 64)
    loose = SimConfig(n=64, topology="full", algorithm="push-sum",
                      mass_tolerance=10.0, chunk_rounds=8)
    r = run(topo, loose, start_state=_ps_state(64, "mass"), start_round=0)
    assert r.outcome == "converged"


def test_sentinel_mid_run_offending_round_is_exact():
    # Trip at a known round: resume a healthy run whose mass is nudged
    # past tolerance — the reported round is the first EXECUTED round.
    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="push-sum",
                    mass_tolerance=1e-3, chunk_rounds=4)
    r = run(topo, cfg, start_state=_ps_state(64, "mass"), start_round=42)
    assert r.outcome == "unhealthy" and r.unhealthy_round == 42


def test_sentinel_fused_rejected_and_auto_demoted():
    cfg = SimConfig(n=1000, topology="full", algorithm="push-sum",
                    delivery="pool", engine="fused", mass_tolerance=1e-3,
                    chunk_rounds=16, max_rounds=400)
    with pytest.raises(ValueError, match="sentinel|mass"):
        run(build_topology("full", 1000), cfg)
    # auto demotes to chunked and still honors the sentinel contract.
    import dataclasses

    r = run(build_topology("full", 1000),
            dataclasses.replace(cfg, engine="auto"))
    assert r.outcome in ("converged", "max_rounds")


def test_sentinel_rejected_by_replica_sweep():
    from cop5615_gossip_protocol_tpu.models.sweep import run_replicas

    with pytest.raises(ValueError, match="sentinel|unbatched"):
        run_replicas(
            build_topology("full", 64),
            SimConfig(n=64, topology="full", algorithm="push-sum",
                      mass_tolerance=1e-3),
            2,
        )


# ------------------------------------------------------ degradation ladder


def _fail_sharded(monkeypatch, exc_factory):
    from cop5615_gossip_protocol_tpu.parallel import sharded

    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        raise exc_factory(calls["n"])

    monkeypatch.setattr(sharded, "run_sharded", boom)
    return calls


def test_ladder_degrades_sharded_to_single_device(monkeypatch):
    monkeypatch.setenv("GOSSIP_TPU_STRICT_ENGINE", "0")
    monkeypatch.setenv("GOSSIP_TPU_RETRY_BASE_S", "0")
    _fail_sharded(monkeypatch, lambda n: RuntimeError("XLA compile exploded"))
    events = []
    topo = build_topology("full", 128)
    cfg = SimConfig(n=128, topology="full", algorithm="gossip",
                    n_devices=4, chunk_rounds=16)
    r = run(topo, cfg, on_event=lambda ev, **f: events.append((ev, f)))
    assert r.converged and r.outcome == "converged"
    assert r.degradations, "rung walk must be recorded on the result"
    assert "devices=1" in r.degradations[-1]["to"]
    assert all(ev == "engine-degraded" for ev, _ in events) and events
    # The degraded answer equals the single-device run (the ladder
    # preserves semantics).
    solo = run(topo, SimConfig(n=128, topology="full", algorithm="gossip",
                               chunk_rounds=16))
    assert (r.rounds, r.converged_count) == (solo.rounds, solo.converged_count)
    rec = metrics.run_record(cfg, topo, r)
    assert rec["degradations"] == r.degradations  # JSONL-visible


def test_ladder_transient_errors_retry_before_degrading(monkeypatch):
    monkeypatch.setenv("GOSSIP_TPU_STRICT_ENGINE", "0")
    monkeypatch.setenv("GOSSIP_TPU_RETRY_BASE_S", "0")
    from cop5615_gossip_protocol_tpu.parallel import sharded

    real = sharded.run_sharded
    calls = {"n": 0}

    def flaky(*a, **k):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise RuntimeError("tunnel UNAVAILABLE: device dropped")
        return real(*a, **k)

    monkeypatch.setattr(sharded, "run_sharded", flaky)
    cfg = SimConfig(n=128, topology="full", algorithm="gossip",
                    n_devices=4, chunk_rounds=16)
    r = run(build_topology("full", 128), cfg)
    # Two transient failures retried on the SAME rung: no degradation.
    assert calls["n"] == 3
    assert r.degradations is None
    assert r.converged


def test_strict_engine_env_restores_fail_fast(monkeypatch):
    monkeypatch.setenv("GOSSIP_TPU_STRICT_ENGINE", "1")
    _fail_sharded(monkeypatch, lambda n: RuntimeError("XLA compile exploded"))
    with pytest.raises(RuntimeError, match="exploded"):
        run(build_topology("full", 128),
            SimConfig(n=128, topology="full", n_devices=4))


def test_strict_engine_cfg_flag(monkeypatch):
    monkeypatch.delenv("GOSSIP_TPU_STRICT_ENGINE", raising=False)
    _fail_sharded(monkeypatch, lambda n: RuntimeError("XLA compile exploded"))
    with pytest.raises(RuntimeError, match="exploded"):
        run(build_topology("full", 128),
            SimConfig(n=128, topology="full", n_devices=4,
                      strict_engine=True))


def test_value_errors_never_degrade(monkeypatch):
    # Config-contract violations fail fast even with the ladder armed: a
    # silently degraded answer to an invalid request would mask the bug.
    monkeypatch.setenv("GOSSIP_TPU_STRICT_ENGINE", "0")
    with pytest.raises(ValueError, match="telemetry"):
        run(build_topology("full", 1000),
            SimConfig(n=1000, topology="full", delivery="pool",
                      engine="fused", n_devices=2, telemetry=True))


def test_ladder_bottom_rung_reraises(monkeypatch):
    # Nothing below single-device chunked: the error propagates (as a
    # real traceback — there is no structured outcome left to produce).
    monkeypatch.setenv("GOSSIP_TPU_STRICT_ENGINE", "0")
    monkeypatch.setenv("GOSSIP_TPU_RETRY_BASE_S", "0")
    monkeypatch.setattr(
        runner, "_run_resolved",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("engine dead")),
    )
    with pytest.raises(RuntimeError, match="engine dead"):
        run(build_topology("full", 64), SimConfig(n=64, topology="full"))


def test_engine_desc_and_ladder_shape():
    cfg = SimConfig(n=64, topology="full", engine="fused", n_devices=4,
                    delivery="pool")
    rungs = runner._engine_ladder(cfg)
    assert [runner._engine_desc(c) for c in rungs] == [
        "engine=fused/devices=4",
        "engine=chunked/devices=4",
        "engine=chunked/devices=1",
    ]
    assert runner._engine_ladder(SimConfig(n=64, topology="full",
                                           engine="chunked")) == [
        SimConfig(n=64, topology="full", engine="chunked")
    ]


# --------------------------------------------------------------- CLI surface


def test_cli_sentinel_tripped_event_and_unhealthy_exit(tmp_path):
    # End to end through the CLI: a resumed corrupt checkpoint trips the
    # sentinel; the run exits nonzero with outcome=unhealthy in the JSONL
    # record and a sentinel-tripped event in the log — never a traceback.
    import json

    from cop5615_gossip_protocol_tpu.cli import main
    from cop5615_gossip_protocol_tpu.utils import checkpoint as ckpt
    from cop5615_gossip_protocol_tpu.utils.events import read_events

    cfg = SimConfig(n=64, topology="full", algorithm="push-sum",
                    mass_tolerance=1e-3, chunk_rounds=8)
    ck = tmp_path / "ck.npz"
    ckpt.save(ck, _ps_state(64, "nan"), 5, cfg)
    ev = tmp_path / "events.jsonl"
    rec_path = tmp_path / "rec.jsonl"
    rc = main(["64", "full", "push-sum", "--mass-tolerance", "1e-3",
               "--chunk-rounds", "8", "--resume", str(ck),
               "--events", str(ev), "--jsonl", str(rec_path), "--quiet"])
    assert rc == 1
    rec = json.loads(rec_path.read_text().splitlines()[-1])
    assert rec["outcome"] == "unhealthy" and rec["unhealthy_round"] == 5
    kinds = [e["event"] for e in read_events(ev)]
    assert "sentinel-tripped" in kinds
    assert kinds[-1] == "run-end"


def test_cli_lint_warning_lands_in_run_start_event(tmp_path, capsys):
    from cop5615_gossip_protocol_tpu.cli import main
    from cop5615_gossip_protocol_tpu.utils.events import read_events

    ev = tmp_path / "events.jsonl"
    with pytest.warns(RuntimeWarning, match="quorum"):
        rc = main(["64", "full", "gossip", "--quorum", "0.5",
                   "--events", str(ev), "--quiet"])
    assert rc == 0
    assert "quorum" in capsys.readouterr().err
    start = read_events(ev)[0]
    assert start["event"] == "run-start"
    assert any("quorum" in w for w in start["warnings"])
