"""Pooled extra-edge delivery for imp2d/imp3d (ops/delivery.deliver_imp_pool,
models/runner._make_imp_pool_round_fn).

The imp topologies are a lattice plus one random long-range edge per node
(program.fs:308-310). Pooled mode re-draws the long-range target per round
from K shared displacements, turning the round into rolls only. Oracles:

- imp_split correctness: lattice offsets match the grid displacement set;
  the extra slot is the last live slot of every row;
- delivery equivalence: the class-roll inbox must equal a scatter-add over
  the materialized targets (exact for int channels, float-order tolerance
  for f32 — the same contract as deliver_stencil/deliver_pool);
- mass conservation per round;
- convergence equivalence: pooled imp must converge in a comparable number
  of rounds to the static-iid graph under scatter delivery, with the same
  estimate quality (the same statistical contract test_pool.py pins for the
  implicit full topology's pool recast);
- config gating: reference semantics and non-imp topologies reject pool.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import imp_pool_parts, run
from cop5615_gossip_protocol_tpu.ops import delivery, sampling
from cop5615_gossip_protocol_tpu.ops.topology import imp_split


def _parts(kind, n, seed, rnd, K=4):
    topo = build_topology(kind, n, seed=seed)
    split = imp_split(topo)
    assert split is not None
    cfg = SimConfig(n=n, topology=kind, algorithm="push-sum",
                    delivery="pool", pool_size=K, seed=seed)
    kr = sampling.round_key(jax.random.PRNGKey(seed), rnd)
    d, is_extra, choice, offs, send_ok = imp_pool_parts(
        topo, cfg, kr, jnp.asarray(split.disp_cols), jnp.asarray(split.degree)
    )
    return topo, split, d, is_extra, choice, offs, send_ok


@pytest.mark.parametrize("kind,n", [("imp2d", 400), ("imp3d", 729)])
def test_imp_split_structure(kind, n):
    topo = build_topology(kind, n, seed=3)
    split = imp_split(topo)
    assert split is not None
    # Extra slot is the last live slot; its displacement is sentineled -1.
    for i in range(topo.n):
        deg = int(topo.degree[i])
        assert deg >= 1
        assert split.disp_cols[i, deg - 1] == -1
        for k in range(deg - 1):
            assert split.disp_cols[i, k] in split.lattice_offsets
    # imp3d lattice classes are the 3D grid set {±1, ±g, ±g²} mod n.
    if kind == "imp3d":
        g = round(topo.n ** (1 / 3))
        want = sorted({d % topo.n for d in
                       (1, -1, g, -g, g * g, -g * g)})
        assert sorted(int(x) for x in split.lattice_offsets) == want


@pytest.mark.parametrize("kind,n", [("imp2d", 300), ("imp3d", 512)])
def test_imp_pool_delivery_matches_scatter(kind, n):
    # Materialize each node's implied target and scatter-deliver; the roll
    # path must agree (int exact, float to summation order).
    for seed, rnd in [(0, 0), (1, 7), (2, 123)]:
        topo, split, d, is_extra, choice, offs, send_ok = _parts(kind, n, seed, rnd)
        n = topo.n
        ids = jnp.arange(n, dtype=jnp.int32)
        lattice_t = jnp.remainder(ids + d, n)
        pool_t = jnp.remainder(ids + offs[choice], n)
        targets = jnp.where(is_extra, pool_t, lattice_t)
        vals_i = jnp.where(send_ok, 1, 0).astype(jnp.int32)
        vals_f = jnp.where(send_ok, jnp.arange(n, dtype=jnp.float32) * 0.5, 0.0)
        inbox = delivery.deliver_imp_pool(
            jnp.stack([vals_i.astype(jnp.float32), vals_f]),
            d, is_extra, choice,
            tuple(int(q) for q in split.lattice_offsets), offs,
        )
        want_i = delivery.deliver(vals_i, targets, n)
        want_f = delivery.deliver(vals_f, targets, n)
        assert (np.asarray(inbox[0]).astype(np.int64) == np.asarray(want_i)).all()
        np.testing.assert_allclose(
            np.asarray(inbox[1]), np.asarray(want_f), rtol=1e-6, atol=1e-4
        )


def test_imp_pool_mass_conservation():
    topo, split, d, is_extra, choice, offs, send_ok = _parts("imp3d", 729, 5, 2)
    n = topo.n
    s = jnp.arange(n, dtype=jnp.float32)
    w = jnp.ones((n,), jnp.float32)
    s_send = jnp.where(send_ok, s * 0.5, 0.0)
    w_send = jnp.where(send_ok, w * 0.5, 0.0)
    inbox = delivery.deliver_imp_pool(
        jnp.stack([s_send, w_send]), d, is_extra, choice,
        tuple(int(q) for q in split.lattice_offsets), offs,
    )
    s_new = (s - s_send) + inbox[0]
    w_new = (w - w_send) + inbox[1]
    np.testing.assert_allclose(float(jnp.sum(s_new)), float(jnp.sum(s)), rtol=1e-6)
    np.testing.assert_allclose(float(jnp.sum(w_new)), float(jnp.sum(w)), rtol=1e-6)


@pytest.mark.parametrize("kind,n", [("imp2d", 1024), ("imp3d", 1728)])
def test_imp_pool_pushsum_convergence_comparable_to_static(kind, n):
    # The semantic contract: per-round rewiring from the pool must not
    # degrade convergence vs the build-time static extra edge under scatter
    # delivery. (Fresh randomness per round mixes at least as well; the
    # bound is generous because round counts are seed-noisy at this size.)
    base = dict(n=n, topology=kind, algorithm="push-sum", max_rounds=20000)
    r_static = run(build_topology(kind, n, seed=11),
                   SimConfig(delivery="scatter", **base))
    r_pool = run(build_topology(kind, n, seed=11),
                 SimConfig(delivery="pool", pool_size=4, **base))
    assert r_static.converged and r_pool.converged
    assert r_pool.rounds <= int(r_static.rounds * 1.6) + 5
    assert r_pool.estimate_mae < 1e-2
    assert r_pool.converged_count == r_pool.population


def test_imp_pool_gossip_converges_with_suppression():
    n = 1331
    cfg = SimConfig(n=n, topology="imp3d", algorithm="gossip",
                    delivery="pool", suppress_converged=True, max_rounds=20000)
    r = run(build_topology("imp3d", n), cfg)
    assert r.converged and r.converged_count == r.population


def test_imp_pool_determinism():
    n = 512
    cfg = SimConfig(n=n, topology="imp3d", algorithm="push-sum",
                    delivery="pool", seed=9, max_rounds=20000)
    r1 = run(build_topology("imp3d", n, seed=9), cfg)
    r2 = run(build_topology("imp3d", n, seed=9), cfg)
    assert r1.converged
    assert r1.rounds == r2.rounds
    assert r1.estimate_mae == r2.estimate_mae


def test_imp_pool_rejects_reference_semantics():
    cfg = SimConfig(n=400, topology="imp3d", algorithm="gossip",
                    semantics="reference", delivery="pool")
    with pytest.raises(ValueError, match="static extra edge"):
        run(build_topology("imp3d", 400, semantics="reference"), cfg)


def test_pool_rejects_non_imp_explicit_topology():
    with pytest.raises(ValueError, match="imp2d/imp3d"):
        SimConfig(n=400, topology="line", algorithm="gossip", delivery="pool")


def test_imp_pool_sharded_gossip_bitwise():
    # Sharded imp-pool: lattice halo rolls + dynamic pool rolls. Gossip
    # trajectories must match single-device exactly at any device count.
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh
    from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded

    n = 1728  # 12^3, divides 8 devices
    topo = build_topology("imp3d", n, seed=3)
    cfg = SimConfig(n=n, topology="imp3d", algorithm="gossip",
                    delivery="pool", suppress_converged=True, seed=3,
                    max_rounds=20000)
    r1 = run(topo, cfg)
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert r1.converged and r8.converged
    assert r8.rounds == r1.rounds
    assert r8.converged_count == r1.converged_count


def test_imp_pool_sharded_pushsum_matches():
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh
    from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded

    n = 1024  # 32^2 imp2d, divides 8
    topo = build_topology("imp2d", n, seed=5)
    cfg = SimConfig(n=n, topology="imp2d", algorithm="push-sum",
                    delivery="pool", seed=5, max_rounds=20000)
    r1 = run(topo, cfg)
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert r1.converged and r8.converged
    # Same per-class accumulation order -> round counts align.
    assert r8.rounds == r1.rounds
    assert abs(r8.estimate_mae - r1.estimate_mae) < 1e-3


def test_imp_pool_sharded_rejects_indivisible():
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh
    from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded

    cfg = SimConfig(n=729, topology="imp3d", algorithm="push-sum",
                    delivery="pool", n_devices=2)
    with pytest.raises(ValueError, match="divide the mesh"):
        run_sharded(build_topology("imp3d", 729), cfg, mesh=make_mesh(2))
