"""HBM-streaming stencil x sharded composition
(parallel/fused_hbm_sharded.py), interpret mode on the 8-virtual-CPU-device
mesh — since ISSUE 9 these oracles pin the ONE-SWEEP round body (raw
state windows + in-consumer mark regen, no delivery planes) on the
batched-ppermute fallback transport; the in-kernel-DMA transport shares
every line of the round body and is comm-audited hardware-free
(tests/test_comm_audit.py) and executed by tests_tpu/ on hardware.

Contracts (VERDICT r4 #1 + #8):
- chunk_rounds=1 degenerates to exact per-round detection and gossip
  trajectories are BITWISE the single-device engines' — wrap (torus3d,
  Z > 0 blend), Z = 0 (ring), and non-wrap (grid2d signed windows);
- at larger CR, convergence is detected at the first super-step boundary
  at/after the true round, never before;
- push-sum follows the single-device trajectory to float tolerance over a
  fixed budget and conserves mass through the halo exchange;
- termination='global' stops at the EXACT verdict round (the psum'd
  per-round unstable vector + capped deterministic rerun), matching the
  chunked sharded global path at any chunk_rounds;
- the runner tiers the compositions like the single-device engines: VMEM
  composition while the shard fits, HBM-streaming past it — sharding
  multiplies the population ceiling instead of shrinking it.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.parallel import fused_sharded
from cop5615_gossip_protocol_tpu.parallel.fused_hbm_sharded import (
    plan_stencil_hbm_sharded,
    run_stencil_hbm_sharded,
)
from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh

# Interpret-mode Pallas oracle: bitwise engine validation that cannot
# fit the ROADMAP tier-1 wall-clock budget on a CPU-only container (the
# kernels run under the Pallas interpreter). Full-suite / TPU runs
# execute it: `pytest tests/` (no -m filter) or `pytest -m slow`.
pytestmark = pytest.mark.slow

# torus g=50: padded layout 1024 rows -> two 512-row shards; Z > 0 so the
# runtime mod-n blend (nonuniform-tile second windows) is live.
N = 125000


def _grab(final, tag):
    def f(rounds, state):
        final[tag] = state
    return f


def _mesh2():
    return make_mesh(2)


def _hbm_run(topo, cfg, mesh, **kw):
    return run_stencil_hbm_sharded(topo, cfg, mesh=mesh, **kw)


def test_gossip_cr1_bitwise_vs_single_device():
    topo = build_topology("torus3d", N)
    final = {}
    r1 = run(topo, SimConfig(n=N, topology="torus3d", algorithm="gossip",
                             engine="chunked", max_rounds=3000),
             on_chunk=_grab(final, "c"))
    cfg = SimConfig(n=N, topology="torus3d", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=1,
                    max_rounds=3000)
    r2 = _hbm_run(topo, cfg, _mesh2(), on_chunk=_grab(final, "f"))
    assert r1.rounds == r2.rounds
    assert r1.converged_count == r2.converged_count
    for f in ("count", "active", "conv"):
        a = np.asarray(getattr(final["c"], f))
        b = np.asarray(getattr(final["f"], f))[:N]
        assert (a == b).all(), f


def test_gossip_grid2d_nonwrap_bitwise():
    # Non-wrap lattice: single signed windows, boundary live-masks.
    n = 131044  # 362^2 -> 1024-row layout -> two 512-row shards
    topo = build_topology("grid2d", n)
    r1 = run(topo, SimConfig(n=n, topology="grid2d", algorithm="gossip",
                             engine="chunked", max_rounds=5000))
    cfg = SimConfig(n=n, topology="grid2d", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=1,
                    max_rounds=5000)
    r2 = _hbm_run(topo, cfg, _mesh2())
    assert r1.rounds == r2.rounds
    assert r1.converged_count == r2.converged_count


def test_gossip_ring_z0_counts_match():
    # Z = 0: both blend variants coincide -> single windows on a wrap kind.
    n = 65536
    topo = build_topology("ring", n)
    r1 = run(topo, SimConfig(n=n, topology="ring", algorithm="gossip",
                             engine="chunked", max_rounds=60))
    cfg = SimConfig(n=n, topology="ring", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=1,
                    max_rounds=60)
    r2 = _hbm_run(topo, cfg, _mesh2())
    assert r1.rounds == r2.rounds
    assert r1.converged_count == r2.converged_count


def test_gossip_cr_adaptive_converges_at_boundary():
    topo = build_topology("torus3d", N)
    r1 = run(topo, SimConfig(n=N, topology="torus3d", algorithm="gossip",
                             engine="chunked", max_rounds=3000))
    cfg = SimConfig(n=N, topology="torus3d", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=8,
                    max_rounds=3000)
    plan = plan_stencil_hbm_sharded(topo, cfg, 2)
    assert not isinstance(plan, str)
    cr = plan[2]
    r3 = _hbm_run(topo, cfg, _mesh2())
    assert r3.converged
    assert r1.rounds <= r3.rounds <= r1.rounds + cr


def test_gossip_bitwise_vs_chunked_sharded_engine():
    # The ISSUE 9 acceptance pin: the one-sweep composition's trajectory
    # is bitwise the chunked SHARDED engine's (not just the single-device
    # chunked path) — same mesh, same shard boundaries, the halo wire the
    # only difference in delivery machinery.
    from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded

    topo = build_topology("torus3d", N)
    final = {}
    cfg_x = SimConfig(n=N, topology="torus3d", algorithm="gossip",
                      engine="chunked", n_devices=2, max_rounds=3000)
    r1 = run_sharded(topo, cfg_x, mesh=_mesh2(), on_chunk=_grab(final, "x"))
    cfg_f = SimConfig(n=N, topology="torus3d", algorithm="gossip",
                      engine="fused", n_devices=2, chunk_rounds=1,
                      max_rounds=3000)
    r2 = _hbm_run(topo, cfg_f, _mesh2(), on_chunk=_grab(final, "f"))
    assert r1.rounds == r2.rounds
    assert r1.converged_count == r2.converged_count
    for f in ("count", "active", "conv"):
        a = np.asarray(getattr(final["x"], f))[:N]
        b = np.asarray(getattr(final["f"], f))[:N]
        assert (a == b).all(), f


def test_pushsum_fixed_rounds_trajectory_and_mass():
    topo = build_topology("torus3d", N)
    final = {}
    rp1 = run(topo, SimConfig(n=N, topology="torus3d", algorithm="push-sum",
                              engine="chunked", max_rounds=64,
                              chunk_rounds=64),
              on_chunk=_grab(final, "c"))
    cfg = SimConfig(n=N, topology="torus3d", algorithm="push-sum",
                    engine="fused", n_devices=2, chunk_rounds=8,
                    max_rounds=64)
    rp2 = _hbm_run(topo, cfg, _mesh2(), on_chunk=_grab(final, "f"))
    assert rp1.rounds == rp2.rounds == 64
    a, b = final["c"], final["f"]
    np.testing.assert_allclose(np.asarray(a.s), np.asarray(b.s)[:N],
                               rtol=2e-5, atol=1e-3)
    np.testing.assert_allclose(np.asarray(a.w), np.asarray(b.w)[:N],
                               rtol=2e-5, atol=1e-6)
    sm = float(np.asarray(b.s, np.float64)[:N].sum())
    true = N * (N - 1) / 2
    assert abs(sm - true) / true < 1e-5
    wm = float(np.asarray(b.w, np.float64)[:N].sum())
    assert abs(wm - N) / N < 1e-5


def test_pushsum_global_exact_vs_chunked_sharded():
    # The global verdict composes across shards: psum'd per-round unstable
    # vector + capped rerun -> the stop round is EXACT at CR > 1, matching
    # the chunked sharded global path. A fat delta keeps the interpret-mode
    # round count small; the guard asserts the verdict actually fired.
    base = dict(n=N, topology="torus3d", algorithm="push-sum",
                termination="global", delta=1e-1, n_devices=2,
                max_rounds=2000)
    topo = build_topology("torus3d", N)
    a = run(topo, SimConfig(engine="chunked", chunk_rounds=64, **base))
    assert a.converged and a.rounds > 1
    cfg = SimConfig(engine="fused", chunk_rounds=8, **base)
    b = _hbm_run(topo, cfg, _mesh2())
    assert b.converged
    assert a.rounds == b.rounds, (a.rounds, b.rounds)
    assert b.converged_count == N


def test_overlap_on_off_bitwise_fixed_rounds():
    # Batched wires + deferred verdict vs the serial schedule on the HBM
    # streaming composition: fixed-round push-sum state must be bitwise
    # schedule-invariant (pure scheduling, same kernel operands).
    topo = build_topology("torus3d", N)
    final, res = {}, {}
    for ov in (True, False):
        cfg = SimConfig(n=N, topology="torus3d", algorithm="push-sum",
                        engine="fused", n_devices=2, chunk_rounds=8,
                        max_rounds=16, overlap_collectives=ov)
        res[ov] = _hbm_run(topo, cfg, _mesh2(), on_chunk=_grab(final, ov))
    assert res[True].rounds == res[False].rounds == 16
    for f in ("s", "w", "term", "conv"):
        a = np.asarray(getattr(final[True], f))
        b = np.asarray(getattr(final[False], f))
        assert (a == b).all(), f


def test_overlap_deferred_verdict_converging_run():
    # A converging gossip run through the deferred-verdict loop: rounds and
    # counts must match the serial schedule exactly (mid-dispatch fire).
    topo = build_topology("torus3d", N)
    res = {}
    for ov in (True, False):
        cfg = SimConfig(n=N, topology="torus3d", algorithm="gossip",
                        engine="fused", n_devices=2, chunk_rounds=8,
                        max_rounds=3000, overlap_collectives=ov)
        res[ov] = _hbm_run(topo, cfg, _mesh2())
    assert res[True].converged and res[False].converged
    assert res[True].rounds == res[False].rounds
    assert res[True].converged_count == res[False].converged_count


def test_resume_midway():
    topo = build_topology("torus3d", N)
    cfg = SimConfig(n=N, topology="torus3d", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=4,
                    max_rounds=3000)
    mesh = _mesh2()
    snaps = []
    full = _hbm_run(topo, cfg, mesh,
                    on_chunk=lambda r, s: snaps.append((r, s)))
    assert len(snaps) >= 2
    r0, s0 = snaps[0]
    resumed = _hbm_run(topo, cfg, mesh,
                       start_state=jax.tree.map(jnp.asarray, s0),
                       start_round=r0)
    assert resumed.rounds == full.rounds
    assert resumed.converged_count == full.converged_count


def test_plan_gating_and_runner_tiering(monkeypatch):
    cfg = SimConfig(n=N, topology="torus3d", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=1,
                    max_rounds=3000)
    # implicit topology has no stencil structure
    assert "displacement" in plan_stencil_hbm_sharded(
        build_topology("full", 1024), cfg, 2
    )
    # imp kinds route to the imp x HBM x sharded composition (ISSUE 10):
    # the refusal names the serving engine and its knob, not a bogus
    # "no displacement columns" claim (imp kinds have a full lattice).
    imp_reason = plan_stencil_hbm_sharded(
        build_topology("imp3d", 27000), cfg, 2
    )
    assert "imp x HBM x sharded" in imp_reason
    assert "delivery='pool'" in imp_reason
    # indivisible layout
    assert "split evenly" in plan_stencil_hbm_sharded(
        build_topology("torus3d", N), cfg, 3
    )
    # Runner tiering: with the VMEM composition's budget collapsed, the
    # dispatch falls through to the HBM-streaming composition and the run
    # still matches the chunked single-device oracle bitwise.
    monkeypatch.setattr(fused_sharded, "_VMEM_BUDGET", 1000)
    plan_v = fused_sharded.plan_fused_sharded(
        build_topology("torus3d", N), cfg, 2
    )
    assert isinstance(plan_v, str)
    r1 = run(build_topology("torus3d", N),
             SimConfig(n=N, topology="torus3d", algorithm="gossip",
                       engine="chunked", max_rounds=3000))
    r2 = run(build_topology("torus3d", N), cfg)
    assert r1.rounds == r2.rounds
    assert r1.converged_count == r2.converged_count
