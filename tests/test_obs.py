"""The unified observability plane (ISSUE 7): the metrics registry and
its Prometheus exposition, histogram quantile error bounds, warm-pool
eviction accounting, the pipelined driver's full run budget, request
trace-id propagation with span closure, the serving ``/metrics``
endpoint, and the wallwalk attribution report's bucket-closure pin."""

import json
import threading

import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.serving import pool as pool_mod
from cop5615_gossip_protocol_tpu.serving.server import ServingApp, make_server
from cop5615_gossip_protocol_tpu.utils import metrics as metrics_mod
from cop5615_gossip_protocol_tpu.utils import obs
from cop5615_gossip_protocol_tpu.utils.events import (
    RunEventLog,
    read_events,
)

# ------------------------------------------------------------- the registry


def test_counter_gauge_labels_and_parse_round_trip():
    r = obs.Registry()
    c = r.counter("foo_total", "a counter")
    c.inc()
    c.inc(2)
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    g = r.gauge("bar", "a gauge", labels=("bucket",))
    g.set(3.5, bucket="a")
    g.set(4, bucket='we"ird')  # exposition must escape label values
    g.set(5, bucket="a\\nb")   # literal backslash + n, NOT a newline —
    g.set(6, bucket="a\nb")    # and a real newline (review finding: the
    # old suffix-order unescape conflated the two)
    parsed = obs.parse_prometheus(r.render())
    assert obs.metric_value(parsed, "foo_total") == 3
    assert obs.metric_value(parsed, "bar", bucket="a") == 3.5
    assert obs.metric_value(parsed, "bar", bucket='we"ird') == 4
    assert obs.metric_value(parsed, "bar", bucket="a\\nb") == 5
    assert obs.metric_value(parsed, "bar", bucket="a\nb") == 6
    assert obs.metric_value(parsed, "nope") is None


def test_registry_rejects_type_and_label_conflicts():
    r = obs.Registry()
    r.counter("x_total", "c")
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("x_total", "g")
    with pytest.raises(ValueError, match="already registered"):
        r.counter("x_total", "c", labels=("k",))
    # The reverse order too: Gauge subclasses Counter, so counter() after
    # gauge() must not silently hand back the gauge (review finding).
    r.gauge("y", "g")
    with pytest.raises(ValueError, match="already registered"):
        r.counter("y", "c")
    with pytest.raises(ValueError, match="already registered"):
        r.histogram("x_total", "h")
    # get-or-create: same spec returns the same instrument.
    assert r.counter("x_total", "c") is r.counter("x_total", "c")
    c = r.counter("y_total", "c", labels=("k",))
    with pytest.raises(ValueError, match="takes labels"):
        c.inc(wrong="v")


def test_histogram_quantile_error_bound_pinned():
    # The documented contract (utils/obs.py): the streaming quantile never
    # under-reports and overestimates by at most a factor of ``growth``,
    # with small-sample tails exact via the min/max clamp.
    import random

    r = obs.Registry()
    h = r.histogram("lat_seconds", "latency")
    rng = random.Random(7)
    vals = [rng.uniform(2e-4, 2.0) for _ in range(2000)]
    for v in vals:
        h.observe(v)
    vals.sort()
    import math

    for q in (0.01, 0.5, 0.9, 0.99, 1.0):
        true = vals[max(0, math.ceil(q * len(vals)) - 1)]
        est = h.quantile(q)
        assert true <= est <= true * h.growth * (1 + 1e-9), (q, true, est)
    assert h.quantile(0.0) >= min(vals)
    assert h.quantile(1.0) == max(vals)
    assert h.count == 2000
    assert h.sum == pytest.approx(sum(vals))
    empty = r.histogram("empty_seconds", "e")
    assert empty.quantile(0.99) is None


def test_histogram_exposition_is_cumulative_and_closed():
    r = obs.Registry()
    h = r.histogram("h_seconds", "h", lo=1e-3, n_buckets=10)
    for v in (1e-4, 5e-3, 5e-3, 123.0):  # under lo, mid, mid, over top
        h.observe(v)
    parsed = obs.parse_prometheus(r.render())
    buckets = parsed["h_seconds_bucket"]
    # Cumulative and monotone, with +Inf == count.
    by_le = sorted(
        ((float(dict(k)["le"].replace("+Inf", "inf")), v)
         for k, v in buckets.items()),
        key=lambda kv: kv[0],
    )
    counts = [v for _, v in by_le]
    assert counts == sorted(counts)
    assert counts[-1] == 4
    assert obs.metric_value(parsed, "h_seconds_count") == 4


def test_collect_callback_refreshes_gauges_at_render():
    r = obs.Registry()
    g = r.gauge("depth", "live depth")
    state = {"v": 0}
    r.add_collect(lambda: g.set(state["v"]))
    state["v"] = 7
    parsed = obs.parse_prometheus(r.render())
    assert obs.metric_value(parsed, "depth") == 7


# --------------------------------- warm-pool eviction accounting (satellite)


def test_pool_eviction_accounting_exact_sequence():
    # Drive the LRU past capacity and pin hit/miss/eviction counters —
    # exposed via the registry — against the exact expected sequence
    # (PR 6 left eviction behavior untested).
    reg = obs.Registry()
    p = pool_mod.WarmEnginePool(capacity=2, registry=reg)

    def mv(name):
        return obs.metric_value(
            obs.parse_prometheus(reg.render()),
            f"gossip_tpu_engine_pool_{name}",
        )

    assert mv("capacity") == 2
    p.get_or_build("a", lambda: "A")     # miss           {a}
    p.get_or_build("b", lambda: "B")     # miss           {a, b}
    p.get_or_build("a", lambda: "A2")    # hit (refresh)  {b, a}
    p.get_or_build("c", lambda: "C")     # miss, evicts b {a, c}
    assert (mv("hits_total"), mv("misses_total"),
            mv("evictions_total")) == (1, 3, 1)
    engine, hit = p.get_or_build("b", lambda: "B2")  # miss, evicts a
    assert (engine, hit) == ("B2", False)
    p.get_or_build("c", lambda: "C2")    # hit            {b, c}
    assert (mv("hits_total"), mv("misses_total"),
            mv("evictions_total")) == (2, 4, 2)
    assert mv("entries") == 2
    # The pool's own stats() stay the same numbers (one source of truth
    # for /stats' engine_pool block).
    s = p.stats()
    assert (s["hits"], s["misses"], s["evictions"]) == (2, 4, 2)


# ---------------------------------------------- the run budget (schema v4)


def test_run_budget_fields_close_and_schema_v4():
    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                    chunk_rounds=8)
    res = run(topo, cfg)
    rec = metrics_mod.run_record(cfg, topo, res)
    assert rec["schema_version"] == metrics_mod.RUN_RECORD_SCHEMA_VERSION == 5
    # The budget identity: residual is exactly the unnamed remainder.
    assert rec["residual_s"] == pytest.approx(
        res.run_s - res.dispatch_s - res.fetch_s - res.hook_s
    )
    # first_dispatch is one of the summed dispatches.
    assert 0 < res.first_dispatch_s <= res.dispatch_s
    assert res.aux_s == 0.0 and res.hook_s == 0.0  # no telemetry, no hooks
    assert len(res.chunk_log) >= 2  # several boundaries at chunk_rounds=8


def test_run_budget_hook_and_aux_buckets_fill():
    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                    chunk_rounds=8, telemetry=True)
    seen = {"chunks": 0}

    def on_chunk(rounds, state):
        seen["chunks"] += 1

    res = run(topo, cfg, on_chunk=on_chunk)
    assert seen["chunks"] >= 2
    assert res.hook_s > 0.0  # the on_chunk bracket measured something
    assert res.aux_s > 0.0  # telemetry collection measured
    assert res.aux_s <= res.fetch_s  # aux is a subset of the fetch block
    assert res.telemetry is not None and res.telemetry.rounds == res.rounds


def test_observe_run_record_and_dump(tmp_path):
    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                    chunk_rounds=8)
    res = run(topo, cfg)
    rec = metrics_mod.run_record(cfg, topo, res)
    reg = obs.Registry()
    obs.observe_run_record(rec, chunk_log=res.chunk_log, registry=reg)
    out = tmp_path / "m.prom"
    obs.dump(out, registry=reg)
    parsed = obs.parse_prometheus(out.read_text())
    assert obs.metric_value(
        parsed, "gossip_tpu_runs_total", outcome="converged") == 1
    assert obs.metric_value(
        parsed, "gossip_tpu_run_rounds_total") == res.rounds
    assert obs.metric_value(
        parsed, "gossip_tpu_run_residual_seconds") == pytest.approx(
        rec["residual_s"])
    assert obs.metric_value(
        parsed, "gossip_tpu_chunk_dispatch_seconds_count") == len(
        res.chunk_log)


def test_cli_metrics_dump_flag(tmp_path, capsys):
    from cop5615_gossip_protocol_tpu.cli import main

    out = tmp_path / "run.prom"
    rc = main(["64", "full", "gossip", "--quiet", "--chunk-rounds", "16",
               "--metrics-dump", str(out)])
    capsys.readouterr()
    assert rc == 0
    parsed = obs.parse_prometheus(out.read_text())
    assert obs.metric_value(
        parsed, "gossip_tpu_runs_total", outcome="converged") >= 1
    for g in ("run_seconds", "dispatch_seconds", "fetch_seconds",
              "first_dispatch_seconds", "residual_seconds"):
        assert obs.metric_value(parsed, f"gossip_tpu_run_{g}") is not None
    # The process-wide registry also carries the pool counters the run
    # populated.
    assert obs.metric_value(
        parsed, "gossip_tpu_engine_pool_misses_total") >= 1


def test_cli_metrics_dump_rejected_for_replica_sweeps(capsys):
    from cop5615_gossip_protocol_tpu.cli import main

    rc = main(["64", "full", "gossip", "--replicas", "2",
               "--metrics-dump", "-"])
    err = capsys.readouterr().err
    assert rc == 2 and "--metrics-dump" in err


# --------------------------- trace ids, spans, /metrics (serving plane)


def test_serving_trace_spans_metrics_and_event_join(tmp_path):
    ev_path = tmp_path / "serve_events.jsonl"
    app = ServingApp(window_s=0.05, max_lanes=8, min_lanes=1,
                     event_log=RunEventLog(ev_path))
    httpd = make_server(app, "127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        # Two concurrent same-bucket requests: distinct trace ids must
        # survive co-batching into one vmapped program.
        results = {}

        def go(i):
            results[i] = app.handle_run(
                {"schema_version": 1, "n": 32, "topology": "full",
                 "algorithm": "gossip", "seed": 100 + i}
            )

        threads = [threading.Thread(target=go, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tids = set()
        for status, resp in results.values():
            assert status == 200, resp
            sv = resp["serving"]
            assert sv["trace_id"]
            tids.add(sv["trace_id"])
            spans = sv["spans"]
            assert set(spans) == {"queue_wait_s", "batch_assemble_s",
                                  "engine_s", "demux_s"}
            # The spans partition the service wall exactly (5% is the CI
            # bar; construction makes it ~float-exact).
            assert sum(spans.values()) == pytest.approx(
                sv["service_ms"] / 1e3, rel=0.05)
            # Every per-request event carries the id.
            assert all(e["trace_id"] == sv["trace_id"]
                       for e in resp["events"])
        assert len(tids) == 2  # distinct identities per request

        # The event-log join: admitted -> batch-retired -> completed, in
        # order, for each trace id (the ISSUE 7 acceptance join).
        events = read_events(ev_path)
        for tid in tids:
            kinds = [e["event"] for e in events
                     if e.get("trace_id") == tid
                     or tid in (e.get("trace_ids") or ())]
            assert kinds.count("request-admitted") == 1, kinds
            assert kinds.count("batch-retired") == 1, kinds
            assert kinds.count("request-completed") == 1, kinds
            assert kinds.index("request-admitted") < kinds.index(
                "batch-retired") < kinds.index("request-completed")

        # GET /metrics under the live server: parseable exposition whose
        # series satisfy the /stats identities at quiescence.
        import http.client

        host, port = httpd.server_address[:2]
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type", "").startswith("text/plain")
        parsed = obs.parse_prometheus(resp.read().decode())
        conn.close()

        def mv(name):
            return obs.metric_value(parsed, f"gossip_tpu_serving_{name}")

        assert mv("received_total") == mv("admitted_total") == 2
        assert mv("completed_total") == 2 and mv("failed_total") == 0
        assert mv("received_total") == (
            mv("admitted_total") + mv("rejected_total")
            + mv("invalid_total"))
        assert mv("batched_requests_total") == (
            mv("completed_total") + mv("failed_total"))
        assert mv("service_seconds_count") == 2
        for span in ("queue_wait", "batch_assemble", "engine", "demux"):
            assert mv(f"{span}_seconds_count") == 2, span
        # The process-wide series (pool) ride the same scrape.
        assert obs.metric_value(
            parsed, "gossip_tpu_engine_pool_misses_total") >= 1
        # /stats percentiles now come from the streaming histogram —
        # present and within the documented bound of the histogram read.
        snap = app.snapshot()
        assert snap["service_ms_p99"] is not None
        assert snap["service_ms_p50"] <= snap["service_ms_p99"]
        p99 = app.stats._h_service.quantile(0.99)
        assert snap["service_ms_p99"] == pytest.approx(1e3 * p99)
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.close()


def test_admission_rejection_carries_trace_id():
    from cop5615_gossip_protocol_tpu.serving.admission import (
        AdmissionError,
        ServingStats,
    )
    from cop5615_gossip_protocol_tpu.serving.batcher import MicroBatcher

    b = MicroBatcher(stats=ServingStats(), queue_limit=1, min_lanes=1)
    # NOT started: the queue fills and the second submit is rejected.
    r1 = b.submit(SimConfig(n=32, topology="full", algorithm="gossip",
                            seed=0, engine="chunked"), False)
    assert r1.trace_id
    with pytest.raises(AdmissionError) as e:
        b.submit(SimConfig(n=32, topology="full", algorithm="gossip",
                           seed=1, engine="chunked"), False)
    assert e.value.trace_id and e.value.trace_id != r1.trace_id
    b.stop(drain=False)


# ------------------------------------------------- wallwalk bucket closure


def test_wallwalk_attribution_closure():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from benchmarks import wallwalk

    rep = wallwalk.walk(
        dict(n=64, topology="full", algorithm="gossip", seed=0,
             chunk_rounds=8, max_rounds=100_000),
        telemetry=True, checkpoint=True,
    )
    assert rep["outcome"] == "converged"
    buckets = rep["buckets"]
    assert set(buckets) == {"init", "build", "compile", "setup",
                            "dispatch", "engine", "aux", "hook",
                            "finalize", "record", "loop*", "harness*"}
    # The directly bracketed phases measured something real; hook/aux
    # exercised by the checkpoint + telemetry knobs.
    assert buckets["hook"] > 0 and buckets["aux"] > 0
    assert buckets["setup"] > 0 and buckets["finalize"] > 0
    # The acceptance pin: >= 90% of the non-engine wall lands in DIRECTLY
    # MEASURED buckets — the subtraction-defined remainders (loop*,
    # harness*) and any unattributed gap count against closure, so the
    # check fails if an unbracketed cost appears (review finding: the
    # earlier all-derived formulation was tautologically 100%).
    assert rep["closure"] >= 0.9, rep
    assert rep["closure"] < 1.0  # the remainders are real, not zeroed
    # ... and the unattributed gap is what closure says it is.
    assert rep["unattributed_s"] == pytest.approx(
        rep["total_s"] - sum(buckets.values()))
    md = wallwalk.render_md(rep)
    assert "closure" in md and "| init |" in md


# ------------------------------------------------------------ trend table


def test_trend_table_renders_and_applies_idempotently(tmp_path):
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from benchmarks import trend

    root = tmp_path
    (root / "BENCH_r01.json").write_text(json.dumps({
        "parsed": {"value": 100.0, "wall_s": 1.5, "compile_s": 2.0,
                   "vs_baseline": 10.0}}))
    (root / "BENCH_r02.json").write_text(json.dumps({
        "parsed": {"value": 200.0, "wall_s": 0.5, "compile_s": 2.5,
                   "engine_us_per_round": 50.0, "vs_baseline": 20.0}}))
    (root / "MULTICHIP_r02.json").write_text(json.dumps({"ok": True}))
    (root / "BENCH_TABLES.md").write_text("# tables\n\n## existing\nrow\n")
    rc = trend.main(["--root", str(root), "--serving", "2:1234", "--apply"])
    assert rc == 0
    text1 = (root / "BENCH_TABLES.md").read_text()
    assert trend.SECTION_HEADER in text1
    assert "| r01 | 100 |" in text1 and "1,234" in text1
    assert "## existing" in text1  # prior sections untouched
    # Idempotent: a second apply replaces, never duplicates.
    rc = trend.main(["--root", str(root), "--serving", "2:1234", "--apply"])
    assert rc == 0
    text2 = (root / "BENCH_TABLES.md").read_text()
    assert text2.count(trend.SECTION_HEADER) == 1
    assert text2 == text1


# ----------------------------- federation + distributed tracing (ISSUE 18)


def test_label_escape_render_parse_render_byte_stable():
    # Satellite 3: the exposition escape (_escape) and the parser's
    # unescape (_unescape) are exact inverses at the BYTE level — render
    # -> parse -> rebuild -> render reproduces the original text for
    # label values containing backslashes, newlines, quotes, and the
    # adversarial backslash-then-n (which must stay two characters, not
    # collapse to a newline).
    r1 = obs.Registry()
    g1 = r1.gauge("weird", "escape torture", labels=("val",))
    for i, v in enumerate((
        "a\\b",        # literal backslash
        "a\nb",        # real newline
        'q"uote',      # double quote
        "back\\nslash",  # backslash + n, NOT a newline
        'mix\\"\n\\\\',  # all three, adjacent
    )):
        g1.set(i, val=v)
    text1 = r1.render()
    series, types, helps = obs.parse_prometheus_typed(text1)
    assert types == {"weird": "gauge"}
    r2 = obs.Registry()
    g2 = r2.gauge("weird", helps["weird"], labels=("val",))
    for key, value in series["weird"].items():
        g2.set(value, **dict(key))
    assert r2.render() == text1


def test_merge_prometheus_by_type_and_determinism():
    # The federation core: counters sum, gauges re-expose per source
    # under the added label, histograms bucket-merge exactly.
    def source(bump):
        r = obs.Registry()
        r.counter("t_total", "c").inc(3 + bump)
        r.gauge("lanes", "g").set(4 + bump)
        h = r.histogram("lat_seconds", "h")
        h.observe(0.01)
        h.observe(0.1 + bump)
        return r.render()

    a, b = source(0), source(2)
    merged = obs.merge_prometheus({"w0": a, "w1": b})
    fed = obs.parse_prometheus(merged)
    assert obs.metric_value(fed, "t_total") == 3 + 5
    assert obs.metric_value(fed, "lanes", worker="w0") == 4
    assert obs.metric_value(fed, "lanes", worker="w1") == 6
    assert obs.metric_value(fed, "lanes") is None  # never summed
    assert obs.metric_value(fed, "lat_seconds_count") == 4
    assert obs.metric_value(fed, "lat_seconds_sum") == pytest.approx(
        obs.metric_value(obs.parse_prometheus(a), "lat_seconds_sum")
        + obs.metric_value(obs.parse_prometheus(b), "lat_seconds_sum"))
    # Bucket-merge is per-le EXACT, not just count-exact.
    pa, pb = obs.parse_prometheus(a), obs.parse_prometheus(b)
    for key, val in fed["lat_seconds_bucket"].items():
        le = dict(key)["le"]
        assert val == (
            obs.metric_value(pa, "lat_seconds_bucket", le=le)
            + obs.metric_value(pb, "lat_seconds_bucket", le=le)), le
    # Deterministic: same sources -> byte-identical merge, and the dump
    # federation path can re-merge a merge of one source stably.
    assert obs.merge_prometheus({"w0": a, "w1": b}) == merged
    # The same merger federates --metrics-dump parts by process index.
    by_proc = obs.parse_prometheus(
        obs.merge_prometheus({"0": a, "1": b}, label="process"))
    assert obs.metric_value(by_proc, "lanes", process="1") == 6


def test_merge_prometheus_rejects_geometry_and_type_conflicts():
    r1 = obs.Registry()
    r1.histogram("h_seconds", "h", lo=1e-4, n_buckets=8).observe(0.01)
    r2 = obs.Registry()
    r2.histogram("h_seconds", "h", lo=1e-3, n_buckets=10).observe(0.01)
    with pytest.raises(ValueError, match="bucket geometry differs"):
        obs.merge_prometheus({"a": r1.render(), "b": r2.render()})
    r3 = obs.Registry()
    r3.counter("x_total", "c").inc()
    r4 = obs.Registry()
    r4.gauge("x_total", "g").set(1)
    with pytest.raises(ValueError, match="refusing to merge"):
        obs.merge_prometheus({"a": r3.render(), "b": r4.render()})


def test_observe_run_record_telemetry_and_plan_events():
    # Satellite 2: --metrics-dump observes the PR 16 byzantine telemetry
    # aggregates and the PR 17 plan-chosen event.
    import numpy as np

    class FakeTelemetry:
        columns = ("rounds", "byzantine_count")
        data = np.array([[1, 0], [2, 3], [3, 0], [4, 4]])

    reg = obs.Registry()
    obs.observe_run_record(
        {"outcome": "converged", "rounds": 4},
        chunk_log=(), registry=reg, telemetry=FakeTelemetry(),
        events=[
            ("run-start", {}),
            ("plan-chosen", {"winner": "chunked",
                             "predicted_us_per_round": 9.25}),
        ],
    )
    parsed = obs.parse_prometheus(reg.render())
    assert obs.metric_value(
        parsed, "gossip_tpu_run_byzantine_node_rounds") == 7
    assert obs.metric_value(
        parsed, "gossip_tpu_run_byzantine_rounds") == 2
    assert obs.metric_value(
        parsed, "gossip_tpu_plan_chosen_total", winner="chunked") == 1
    assert obs.metric_value(
        parsed, "gossip_tpu_plan_predicted_us_per_round") == 9.25


def test_metrics_endpoint_stays_200_while_draining():
    # Satellite 1: scraping a lame duck must never 503 — /healthz flips,
    # /metrics keeps answering with the full exposition.
    import http.client

    app = ServingApp(window_s=0.05, max_lanes=8, min_lanes=1)
    httpd = make_server(app, "127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        status, _resp = app.handle_run(
            {"schema_version": 1, "n": 32, "topology": "full",
             "algorithm": "gossip", "seed": 11})
        assert status == 200
        app.begin_drain(0.1)
        host, port = httpd.server_address[:2]
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("GET", "/healthz")
        r = conn.getresponse()
        body = json.loads(r.read())
        assert r.status == 503 and body["draining"] is True
        conn.request("GET", "/metrics")
        r = conn.getresponse()
        text = r.read().decode()
        conn.close()
        assert r.status == 200
        assert r.getheader("Content-Type", "").startswith("text/plain")
        parsed = obs.parse_prometheus(text)
        assert obs.metric_value(
            parsed, "gossip_tpu_serving_completed_total") == 1
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.close()


def test_hash_ring_arc_fractions_sum_to_one():
    from cop5615_gossip_protocol_tpu.serving.fleet import HashRing

    ring = HashRing()
    for wid in ("w0", "w1", "w2"):
        ring.add(wid)
    fracs = ring.arc_fractions()
    assert set(fracs) == {"w0", "w1", "w2"}
    assert all(f > 0 for f in fracs.values())
    assert sum(fracs.values()) == pytest.approx(1.0, abs=1e-9)
    ring.remove("w1")
    fracs = ring.arc_fractions()
    assert set(fracs) == {"w0", "w2"}
    assert sum(fracs.values()) == pytest.approx(1.0, abs=1e-9)


class _FakeWorker:
    """The FleetFront worker interface over an in-process ServingApp —
    the tier-1 stand-in for a serve.py OS process (same request_line /
    metrics / alive contract WorkerProc implements over sockets)."""

    def __init__(self, worker_id, app):
        self.worker_id = worker_id
        self.app = app
        self.killed = False

    def alive(self):
        return not self.killed

    def request_line(self, raw):
        if self.killed:
            raise OSError(f"worker {self.worker_id} is dead")
        status, resp = self.app.handle_run(json.loads(raw))
        resp = dict(resp)
        resp.setdefault("status", status)
        return json.dumps(resp).encode()

    def drop_conns(self):
        pass

    def metrics(self):
        if self.killed:
            raise OSError(f"worker {self.worker_id} is dead")
        return self.app.metrics_text()


def test_fleet_trace_join_reroute_and_federated_metrics(tmp_path):
    # The ISSUE 18 acceptance pin, in-process: a 2-worker fleet, the
    # bucket's home worker killed between requests, the rerouted request
    # carrying ONE trace id whose lifecycle joins across the front's and
    # the worker's event logs; front spans + worker service partition the
    # end-to-end wall within 5% FROM THE EVENT LOGS ALONE; and the
    # federated /metrics union holds its identities with a dead worker
    # skipped-and-counted.
    from cop5615_gossip_protocol_tpu.serving.admission import (
        FRONT_SPAN_NAMES,
    )
    from cop5615_gossip_protocol_tpu.serving.fleet import FleetFront

    front_ev = tmp_path / "front.jsonl"
    apps = {
        wid: ServingApp(
            window_s=0.05, max_lanes=8, min_lanes=1,
            event_log=RunEventLog(tmp_path / f"worker.{wid}.jsonl"),
        )
        for wid in ("w0", "w1")
    }
    workers = {wid: _FakeWorker(wid, app) for wid, app in apps.items()}
    front = FleetFront(list(workers.values()), quarantine_s=60.0,
                       events_path=str(front_ev))
    try:
        body = {"schema_version": 1, "n": 32, "topology": "full",
                "algorithm": "gossip", "seed": 5}
        r1 = front.handle_body(dict(body))
        assert r1.get("status", 200) == 200, r1
        owner = r1["fleet"]["worker"]
        survivor = "w1" if owner == "w0" else "w0"
        assert r1["fleet"]["reroutes"] == 0
        assert r1["fleet"]["trace_id"] == r1["serving"]["trace_id"]

        # Kill the bucket's home worker; the SAME bucket (full is not
        # seed-built — a different seed keeps the bucket key) must
        # re-route to the survivor with the kill observed in retry_s.
        workers[owner].killed = True
        r2 = front.handle_body(
            dict(body, seed=6, trace_id="client-trace-42"))
        assert r2.get("status", 200) == 200, r2
        fl = r2["fleet"]
        assert fl["worker"] == survivor
        assert fl["reroutes"] == 1
        assert fl["trace_id"] == "client-trace-42"  # client id honored
        assert r2["serving"]["trace_id"] == "client-trace-42"
        assert set(fl["spans"]) == set(FRONT_SPAN_NAMES)
        assert fl["spans"]["retry_s"] > 0.0
        assert front.counters["reroutes"] == 1
        assert front.counters["worker_failures"] == 1
        assert front.quarantine.state(owner) == "open"

        # -- the cross-process join, from the event logs alone ------------
        fev = read_events(front_ev)
        rerouted = [e for e in fev if e["event"] == "front-request-rerouted"]
        assert len(rerouted) == 1
        assert rerouted[0]["trace_id"] == "client-trace-42"
        assert rerouted[0]["worker"] == owner  # names the killed attempt
        assert rerouted[0]["attempt"] == 1
        done = [e for e in fev
                if e["event"] == "front-request-completed"
                and e["trace_id"] == "client-trace-42"]
        assert len(done) == 1
        done = done[0]
        assert done["worker"] == survivor and done["reroutes"] == 1
        assert set(done["spans"]) == set(FRONT_SPAN_NAMES)
        # Front spans + the worker's service wall partition the
        # end-to-end wall (the 5% acceptance bar).
        gap = abs(sum(done["spans"].values()) + done["service_s"]
                  - done["wall_s"])
        assert gap <= 0.05 * done["wall_s"], done
        # The worker half: admitted -> batch-retired -> completed under
        # the SAME id, in the survivor's own log.
        wev = read_events(tmp_path / f"worker.{survivor}.jsonl")
        kinds = [e["event"] for e in wev
                 if e.get("trace_id") == "client-trace-42"
                 or "client-trace-42" in (e.get("trace_ids") or ())]
        assert kinds.count("request-admitted") == 1, kinds
        assert kinds.count("batch-retired") == 1, kinds
        assert kinds.count("request-completed") == 1, kinds
        assert kinds.index("request-admitted") < kinds.index(
            "batch-retired") < kinds.index("request-completed")

        # -- the federated scrape with a dead worker ----------------------
        fed = obs.parse_prometheus(front.metrics_text())

        def mv(name, **labels):
            return obs.metric_value(fed, name, **labels)

        # Only the survivor is scrapeable: its serving counters ARE the
        # federated counters; the dead worker is skipped and counted.
        assert mv("gossip_tpu_serving_completed_total") == 1
        assert mv("gossip_tpu_fleet_scrape_skipped_workers") == 1
        assert mv("gossip_tpu_fleet_workers_alive") == 1
        # Gauges re-expose per worker under the added label.
        assert mv("gossip_tpu_serving_in_flight",
                  worker=survivor) == 0
        # Quarantine-as-membership state gauge: 2=open for the corpse.
        assert mv("gossip_tpu_fleet_worker_quarantine_state",
                  worker=owner) == 2
        assert mv("gossip_tpu_fleet_worker_quarantine_state",
                  worker=survivor) == 0
        # Front identities: exactly one response per request; the dead
        # attempt shows up as forwards - responded.
        assert mv("gossip_tpu_fleet_received_total") == 2
        assert mv("gossip_tpu_fleet_responded_total") == 2
        assert mv("gossip_tpu_fleet_forwards_total") == 3
        assert mv("gossip_tpu_fleet_reroutes_total") == 1
        assert mv("gossip_tpu_fleet_worker_failures_total") == 1
        # Ring ownership sums to 1 (both workers still own arcs — the
        # quarantine routes around, membership churn is not removal).
        arcs = [v for k, v in
                fed["gossip_tpu_fleet_ring_arc_fraction"].items()]
        assert sum(arcs) == pytest.approx(1.0, abs=1e-9)
        # Every successful routed request observed all four front spans.
        for span in ("route", "connect", "retry", "reassemble"):
            assert mv(f"gossip_tpu_fleet_{span}_seconds_count") == 2, span
        assert mv("gossip_tpu_fleet_request_seconds_count") == 2
        # Satellite 1, fleet half: the federated scrape keeps working
        # while the front drains (lame-duck must not blind the scraper).
        front.draining = True
        fed2 = obs.parse_prometheus(front.metrics_text())
        assert obs.metric_value(
            fed2, "gossip_tpu_fleet_received_total") == 2
    finally:
        for app in apps.values():
            app.close()


# --------------------------- per-super-step attribution (ISSUE 18 leg c)


def test_step_timing_report_and_straggler_units():
    from cop5615_gossip_protocol_tpu.models import pipeline as pipeline_mod

    log = [
        {"rounds": 8, "wall_s": 0.08},
        {"rounds": 16, "wall_s": 0.24},
        {"other": True},  # a non-timed row (e.g. off-path entry) is skipped
        {"rounds": 24, "wall_s": 0.08},
    ]
    rep = pipeline_mod.step_timing_report(log)
    assert rep["dispatches"] == 3
    assert rep["rounds"] == [8, 16, 24]
    # per-round us: [10000, 30000, 10000] -> median 10000, max 30000.
    assert rep["median_us_per_round"] == pytest.approx(10000.0)
    assert rep["max_us_per_round"] == pytest.approx(30000.0)
    assert rep["straggler"]["processes"] == 1
    assert rep["straggler"]["max_skew_s"] == 0.0
    # No timed rows -> None (the off-path contract).
    assert pipeline_mod.step_timing_report([{"rounds": 8}]) is None
    assert pipeline_mod.step_timing_report([]) is None
    # The multi-process skew join: boundary skews [0.1, 0.4, 0.2].
    st = pipeline_mod.straggler_report(
        {0: [1.0, 2.0, 3.0], 1: [1.1, 2.4, 3.2]})
    assert st["processes"] == 2 and st["boundaries"] == 3
    assert st["max_skew_s"] == pytest.approx(0.4)
    assert st["median_skew_s"] == pytest.approx(0.2)
    # Truncates to the shortest log (a killed process still reports).
    st = pipeline_mod.straggler_report({0: [1.0, 2.0, 3.0], 1: [1.5]})
    assert st["boundaries"] == 1 and st["max_skew_s"] == pytest.approx(0.5)
    assert pipeline_mod.straggler_report({0: [1.0, 2.0]})["max_skew_s"] == 0.0


def test_step_timing_off_path_is_neutral():
    # The flag is clock-only: identical protocol outcome, and the OFF
    # path's chunk_log carries no timing keys at all (bitwise-neutral
    # program — the flag never reaches the traced computation).
    from cop5615_gossip_protocol_tpu.models import pipeline as pipeline_mod

    topo = build_topology("full", 64)
    base = dict(n=64, topology="full", algorithm="gossip", seed=3,
                chunk_rounds=8)
    off = run(topo, SimConfig(**base))
    on = run(topo, SimConfig(**base, step_timing=True))
    assert off.rounds == on.rounds
    assert off.converged == on.converged
    assert off.converged_count == on.converged_count
    assert all("wall_s" not in e and "t_retire" not in e
               for e in off.chunk_log)
    assert len(on.chunk_log) >= 2
    assert all("wall_s" in e for e in on.chunk_log)
    assert pipeline_mod.step_timing_report(off.chunk_log) is None
    rep = pipeline_mod.step_timing_report(on.chunk_log)
    assert rep["dispatches"] == len(on.chunk_log)
    assert rep["median_us_per_round"] > 0


def test_step_timing_refused_under_overlap_schedule():
    # The composition contract: under overlap_collectives the deferred
    # termination psum would have to drain at every timed boundary, so
    # the sharded fused planner refuses LOUDLY instead of silently
    # serializing the overlap window.
    topo = build_topology("torus3d", 125000)
    cfg = SimConfig(n=125000, topology="torus3d", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=8,
                    max_rounds=3000, overlap_collectives=True,
                    step_timing=True)
    with pytest.raises(ValueError, match="step_timing under the overlapped"):
        run(topo, cfg)


def test_observe_step_timing_series():
    reg = obs.Registry()
    obs.observe_step_timing(
        {"dispatches": 3, "wall_s": [0.1, 0.2, 0.3],
         "rounds": [8, 16, 24],
         "median_us_per_round": 12500.0, "max_us_per_round": 37500.0,
         "straggler": {"processes": 2, "boundaries": 3,
                       "max_skew_s": 0.4, "median_skew_s": 0.2}},
        registry=reg,
    )
    parsed = obs.parse_prometheus(reg.render())
    assert obs.metric_value(
        parsed, "gossip_tpu_superstep_wall_seconds_count") == 3
    assert obs.metric_value(
        parsed, "gossip_tpu_superstep_wall_seconds_sum") == pytest.approx(0.6)
    assert obs.metric_value(
        parsed, "gossip_tpu_superstep_median_us_per_round") == 12500.0
    assert obs.metric_value(
        parsed, "gossip_tpu_superstep_max_us_per_round") == 37500.0
    assert obs.metric_value(
        parsed, "gossip_tpu_superstep_straggler_max_skew_seconds") == 0.4
    assert obs.metric_value(
        parsed, "gossip_tpu_superstep_straggler_median_skew_seconds") == 0.2


def test_cli_step_timing_metrics_dump(tmp_path, capsys):
    from cop5615_gossip_protocol_tpu.cli import main

    out = tmp_path / "st.prom"
    rc = main(["64", "full", "gossip", "--quiet", "--chunk-rounds", "16",
               "--step-timing", "--metrics-dump", str(out)])
    capsys.readouterr()
    assert rc == 0
    parsed = obs.parse_prometheus(out.read_text())
    assert obs.metric_value(
        parsed, "gossip_tpu_superstep_wall_seconds_count") >= 1
    assert obs.metric_value(
        parsed, "gossip_tpu_superstep_median_us_per_round") > 0


def test_cli_step_timing_rejected_for_replica_sweeps(capsys):
    from cop5615_gossip_protocol_tpu.cli import main

    rc = main(["64", "full", "gossip", "--replicas", "2", "--step-timing"])
    err = capsys.readouterr().err
    assert rc == 2 and "--step-timing" in err


def test_measured_vs_predicted_joins_with_stub_measure():
    # The join is testable without touching an engine: inject measure().
    from cop5615_gossip_protocol_tpu.analysis import cost

    cal = cost.load_calibration()
    cells = (
        ("full", "gossip", 64, {}),
        ("full", "gossip", 64, {"n_devices": 16}),  # > host devices
        ("line", "gossip", 64, {}),
    )
    measured_cfgs = []

    def fake_measure(topo, cfg):
        measured_cfgs.append((cfg.topology, cfg.n))
        assert cfg.step_timing  # the cell runs with the flag threaded
        if cfg.topology == "line":
            return None  # a run that never retired a timed chunk
        return {"dispatches": 2, "wall_s": [0.1, 0.1], "rounds": [8, 16],
                "median_us_per_round": 50.0, "max_us_per_round": 80.0,
                "straggler": {"processes": 1, "boundaries": 2,
                              "max_skew_s": 0.0, "median_skew_s": 0.0}}

    rows = cost.measured_vs_predicted(cal, cells=cells,
                                      measure=fake_measure)
    assert len(rows) == 2 + len(cells)  # header + rule + one row per cell
    assert "| 50.00 " in rows[2] and "| 80.00 " in rows[2]
    assert "SKIPPED" in rows[3]  # never silently dropped
    assert "UNMEASURED" in rows[4]
    # The skipped cell was never measured.
    assert measured_cfgs == [("full", 64), ("line", 64)]


def test_trend_step_timing_applies_idempotently(tmp_path, monkeypatch,
                                                capsys):
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from benchmarks import trend
    from cop5615_gossip_protocol_tpu.analysis import cost

    def canned(calibration=None, cells=None, measure=None, say=None):
        return [
            "| cell | plan | predicted us/round "
            "| measured median us/round | measured max "
            "| ratio meas/pred |",
            "|---|---|---|---|---|---|",
            "| full/gossip/n=64 | chunked | 10.00 | 12.00 | 15.00 "
            "| 1.20 |",
        ]

    monkeypatch.setattr(cost, "measured_vs_predicted", canned)
    root = tmp_path
    (root / "BENCH_r01.json").write_text(json.dumps({
        "parsed": {"value": 100.0, "wall_s": 1.5, "compile_s": 2.0,
                   "vs_baseline": 10.0}}))
    (root / "BENCH_TABLES.md").write_text("# tables\n\n## existing\nrow\n")
    rc = trend.main(["--root", str(root), "--step-timing", "--apply"])
    capsys.readouterr()
    assert rc == 0
    text1 = (root / "BENCH_TABLES.md").read_text()
    assert trend.STEP_TIMING_HEADER in text1
    assert "| full/gossip/n=64 | chunked | 10.00 |" in text1
    assert "## existing" in text1
    rc = trend.main(["--root", str(root), "--step-timing", "--apply"])
    capsys.readouterr()
    assert rc == 0
    text2 = (root / "BENCH_TABLES.md").read_text()
    assert text2.count(trend.STEP_TIMING_HEADER) == 1
    assert text2 == text1
    # A bare --apply preserves the previously applied section.
    rc = trend.main(["--root", str(root), "--apply"])
    capsys.readouterr()
    assert rc == 0
    text3 = (root / "BENCH_TABLES.md").read_text()
    assert text3.count(trend.STEP_TIMING_HEADER) == 1


def test_trend_ceilings_apply_idempotent_and_preserves_serving(tmp_path):
    # ISSUE 15 satellite: the ceilings section has its own header and its
    # own idempotent apply, and a bare --apply (no --serving flags, no
    # --ceilings) must preserve BOTH the previously applied serving pin
    # and the previously applied ceilings section — a regen can't drop
    # the r14 serving row or the ceilings table (the PR 9
    # pin-preservation rule, extended).
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from benchmarks import trend

    root = tmp_path
    (root / "BENCH_r01.json").write_text(json.dumps({
        "parsed": {"value": 100.0, "wall_s": 1.5, "compile_s": 2.0,
                   "vs_baseline": 10.0}}))
    (root / "BENCH_TABLES.md").write_text("# tables\n\n## existing\nrow\n")
    rc = trend.main(["--root", str(root), "--serving", "1:4321",
                     "--ceilings", "--apply"])
    assert rc == 0
    text1 = (root / "BENCH_TABLES.md").read_text()
    assert trend.CEILINGS_HEADER in text1
    assert "replicated-pool2 (reduce_scatter)" in text1
    assert "replicated-pool2 (all_gather)" in text1
    assert "Host-sharded construction" in text1
    assert "4,321" in text1
    # Second apply WITH ceilings: byte-identical (the plan functions are
    # pure — same table both times).
    rc = trend.main(["--root", str(root), "--serving", "1:4321",
                     "--ceilings", "--apply"])
    assert (root / "BENCH_TABLES.md").read_text() == text1
    # Bare apply (no --serving, no --ceilings): the serving pin survives
    # via the parse-back path, the ceilings section is left untouched.
    rc = trend.main(["--root", str(root), "--apply"])
    assert rc == 0
    text3 = (root / "BENCH_TABLES.md").read_text()
    assert "4,321" in text3
    assert text3.count(trend.CEILINGS_HEADER) == 1
    assert "replicated-pool2 (reduce_scatter)" in text3
    assert "## existing" in text3


# ------------------------------- the durable-state plane (ISSUE 19, v7)


def test_checkpoint_metrics_registry_pins(tmp_path):
    # utils/checkpoint instruments the process-global registry: write /
    # verify / load wall histograms, bytes-written counter, generation
    # gauge, and the quarantine counter. Pin deltas (the registry
    # accumulates across tests in one process).
    import numpy as np

    from cop5615_gossip_protocol_tpu.utils import checkpoint as ckpt

    reg = obs.default_registry()

    def val(name):
        v = obs.metric_value(obs.parse_prometheus(reg.render()), name)
        return 0.0 if v is None else v

    before = {n: val(n) for n in (
        "gossip_tpu_checkpoint_write_seconds_count",
        "gossip_tpu_checkpoint_verify_seconds_count",
        "gossip_tpu_checkpoint_load_seconds_count",
        "gossip_tpu_checkpoint_bytes_written_total",
        "gossip_tpu_checkpoint_quarantined_total",
    )}

    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="push-sum",
                    chunk_rounds=8)
    snaps = []
    run(topo, cfg, on_chunk=lambda r, s: snaps.append((r, s)))
    path = tmp_path / "ck.npz"
    info = ckpt.save(path, snaps[0][1], snaps[0][0], cfg, keep=2)
    ckpt.save(path, snaps[1][1], snaps[1][0], cfg, keep=2)
    ckpt.load(path)

    assert val("gossip_tpu_checkpoint_write_seconds_count") == \
        before["gossip_tpu_checkpoint_write_seconds_count"] + 2
    assert val("gossip_tpu_checkpoint_verify_seconds_count") == \
        before["gossip_tpu_checkpoint_verify_seconds_count"] + 1
    assert val("gossip_tpu_checkpoint_load_seconds_count") == \
        before["gossip_tpu_checkpoint_load_seconds_count"] + 1
    assert val("gossip_tpu_checkpoint_bytes_written_total") >= \
        before["gossip_tpu_checkpoint_bytes_written_total"] + 2 * info["bytes"] * 0.5
    assert val("gossip_tpu_checkpoint_generation") == 1.0  # newest index

    # Quarantine bumps its counter: corrupt the newest generation and walk.
    newest = ckpt.candidate_paths(path)[0]
    newest.write_bytes(newest.read_bytes()[:128])
    assert ckpt.load_latest_intact(path) is not None
    assert val("gossip_tpu_checkpoint_quarantined_total") == \
        before["gossip_tpu_checkpoint_quarantined_total"] + 1


def test_event_vocabulary_v7_checkpoint_events(tmp_path):
    # The v7 vocabulary additions ride the same JSONL plane as v6: every
    # line carries schema_version 7 and read_events round-trips the new
    # checkpoint-written fields plus the two new event types.
    from cop5615_gossip_protocol_tpu.utils.events import EVENT_SCHEMA_VERSION

    assert EVENT_SCHEMA_VERSION == 7

    log = tmp_path / "events.jsonl"
    ev = RunEventLog(log)
    ev.emit("checkpoint-written", rounds=32, path="ck.g000001.npz",
            generation=1, bytes=2048, write_s=0.01)
    ev.emit("checkpoint-corrupt-quarantined", path="ck.g000001.npz",
            reason="data archive is unreadable (truncated or torn write)",
            corrupt_arrays=[], quarantined=["ck.g000001.npz.corrupt"])
    ev.emit("checkpoint-failed", rounds=64,
            error="OSError: [Errno 28] No space left on device")
    recs = read_events(log)
    assert [r["event"] for r in recs] == [
        "checkpoint-written", "checkpoint-corrupt-quarantined",
        "checkpoint-failed"]
    assert all(r["schema_version"] == 7 for r in recs)
    written = recs[0]
    assert {"generation", "bytes", "write_s", "rounds", "path"} <= set(written)
    assert set(recs[1]) >= {"path", "reason", "corrupt_arrays", "quarantined"}
    assert set(recs[2]) >= {"rounds", "error"}


def test_trend_durability_section_applies_idempotently(tmp_path):
    # ISSUE 19 satellite: the durability section has its own header and
    # rides the same idempotent apply as every generated section. The
    # render itself is a fresh measurement (not re-run here — the chaos
    # CI job exercises the real legs); what tier-1 pins is the install
    # machinery: applying one rendered section twice is byte-stable and
    # preserves every neighboring section.
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from benchmarks import trend

    bench = tmp_path / "BENCH_TABLES.md"
    bench.write_text("# tables\n\n## existing\nrow\n\n"
                     f"{trend.STEP_TIMING_HEADER}\nold step rows\n")
    section = (f"{trend.DURABILITY_HEADER}\n\nprose\n\n"
               "| cell | rounds |\n|---|---|\n| gossip full n=256 | 33 |\n")
    trend.apply_to_bench_tables(section, bench,
                                header=trend.DURABILITY_HEADER)
    text1 = bench.read_text()
    assert text1.count(trend.DURABILITY_HEADER) == 1
    assert "## existing" in text1 and "old step rows" in text1
    trend.apply_to_bench_tables(section, bench,
                                header=trend.DURABILITY_HEADER)
    assert bench.read_text() == text1
    # A replacement render swaps the section in place.
    trend.apply_to_bench_tables(
        section.replace("| gossip full n=256 | 33 |",
                        "| gossip full n=256 | 34 |"),
        bench, header=trend.DURABILITY_HEADER)
    text3 = bench.read_text()
    assert text3.count(trend.DURABILITY_HEADER) == 1
    assert "| gossip full n=256 | 34 |" in text3
    assert "| gossip full n=256 | 33 |" not in text3
    assert "old step rows" in text3
