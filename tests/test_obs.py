"""The unified observability plane (ISSUE 7): the metrics registry and
its Prometheus exposition, histogram quantile error bounds, warm-pool
eviction accounting, the pipelined driver's full run budget, request
trace-id propagation with span closure, the serving ``/metrics``
endpoint, and the wallwalk attribution report's bucket-closure pin."""

import json
import threading

import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.serving import pool as pool_mod
from cop5615_gossip_protocol_tpu.serving.server import ServingApp, make_server
from cop5615_gossip_protocol_tpu.utils import metrics as metrics_mod
from cop5615_gossip_protocol_tpu.utils import obs
from cop5615_gossip_protocol_tpu.utils.events import (
    RunEventLog,
    read_events,
)

# ------------------------------------------------------------- the registry


def test_counter_gauge_labels_and_parse_round_trip():
    r = obs.Registry()
    c = r.counter("foo_total", "a counter")
    c.inc()
    c.inc(2)
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    g = r.gauge("bar", "a gauge", labels=("bucket",))
    g.set(3.5, bucket="a")
    g.set(4, bucket='we"ird')  # exposition must escape label values
    g.set(5, bucket="a\\nb")   # literal backslash + n, NOT a newline —
    g.set(6, bucket="a\nb")    # and a real newline (review finding: the
    # old suffix-order unescape conflated the two)
    parsed = obs.parse_prometheus(r.render())
    assert obs.metric_value(parsed, "foo_total") == 3
    assert obs.metric_value(parsed, "bar", bucket="a") == 3.5
    assert obs.metric_value(parsed, "bar", bucket='we"ird') == 4
    assert obs.metric_value(parsed, "bar", bucket="a\\nb") == 5
    assert obs.metric_value(parsed, "bar", bucket="a\nb") == 6
    assert obs.metric_value(parsed, "nope") is None


def test_registry_rejects_type_and_label_conflicts():
    r = obs.Registry()
    r.counter("x_total", "c")
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("x_total", "g")
    with pytest.raises(ValueError, match="already registered"):
        r.counter("x_total", "c", labels=("k",))
    # The reverse order too: Gauge subclasses Counter, so counter() after
    # gauge() must not silently hand back the gauge (review finding).
    r.gauge("y", "g")
    with pytest.raises(ValueError, match="already registered"):
        r.counter("y", "c")
    with pytest.raises(ValueError, match="already registered"):
        r.histogram("x_total", "h")
    # get-or-create: same spec returns the same instrument.
    assert r.counter("x_total", "c") is r.counter("x_total", "c")
    c = r.counter("y_total", "c", labels=("k",))
    with pytest.raises(ValueError, match="takes labels"):
        c.inc(wrong="v")


def test_histogram_quantile_error_bound_pinned():
    # The documented contract (utils/obs.py): the streaming quantile never
    # under-reports and overestimates by at most a factor of ``growth``,
    # with small-sample tails exact via the min/max clamp.
    import random

    r = obs.Registry()
    h = r.histogram("lat_seconds", "latency")
    rng = random.Random(7)
    vals = [rng.uniform(2e-4, 2.0) for _ in range(2000)]
    for v in vals:
        h.observe(v)
    vals.sort()
    import math

    for q in (0.01, 0.5, 0.9, 0.99, 1.0):
        true = vals[max(0, math.ceil(q * len(vals)) - 1)]
        est = h.quantile(q)
        assert true <= est <= true * h.growth * (1 + 1e-9), (q, true, est)
    assert h.quantile(0.0) >= min(vals)
    assert h.quantile(1.0) == max(vals)
    assert h.count == 2000
    assert h.sum == pytest.approx(sum(vals))
    empty = r.histogram("empty_seconds", "e")
    assert empty.quantile(0.99) is None


def test_histogram_exposition_is_cumulative_and_closed():
    r = obs.Registry()
    h = r.histogram("h_seconds", "h", lo=1e-3, n_buckets=10)
    for v in (1e-4, 5e-3, 5e-3, 123.0):  # under lo, mid, mid, over top
        h.observe(v)
    parsed = obs.parse_prometheus(r.render())
    buckets = parsed["h_seconds_bucket"]
    # Cumulative and monotone, with +Inf == count.
    by_le = sorted(
        ((float(dict(k)["le"].replace("+Inf", "inf")), v)
         for k, v in buckets.items()),
        key=lambda kv: kv[0],
    )
    counts = [v for _, v in by_le]
    assert counts == sorted(counts)
    assert counts[-1] == 4
    assert obs.metric_value(parsed, "h_seconds_count") == 4


def test_collect_callback_refreshes_gauges_at_render():
    r = obs.Registry()
    g = r.gauge("depth", "live depth")
    state = {"v": 0}
    r.add_collect(lambda: g.set(state["v"]))
    state["v"] = 7
    parsed = obs.parse_prometheus(r.render())
    assert obs.metric_value(parsed, "depth") == 7


# --------------------------------- warm-pool eviction accounting (satellite)


def test_pool_eviction_accounting_exact_sequence():
    # Drive the LRU past capacity and pin hit/miss/eviction counters —
    # exposed via the registry — against the exact expected sequence
    # (PR 6 left eviction behavior untested).
    reg = obs.Registry()
    p = pool_mod.WarmEnginePool(capacity=2, registry=reg)

    def mv(name):
        return obs.metric_value(
            obs.parse_prometheus(reg.render()),
            f"gossip_tpu_engine_pool_{name}",
        )

    assert mv("capacity") == 2
    p.get_or_build("a", lambda: "A")     # miss           {a}
    p.get_or_build("b", lambda: "B")     # miss           {a, b}
    p.get_or_build("a", lambda: "A2")    # hit (refresh)  {b, a}
    p.get_or_build("c", lambda: "C")     # miss, evicts b {a, c}
    assert (mv("hits_total"), mv("misses_total"),
            mv("evictions_total")) == (1, 3, 1)
    engine, hit = p.get_or_build("b", lambda: "B2")  # miss, evicts a
    assert (engine, hit) == ("B2", False)
    p.get_or_build("c", lambda: "C2")    # hit            {b, c}
    assert (mv("hits_total"), mv("misses_total"),
            mv("evictions_total")) == (2, 4, 2)
    assert mv("entries") == 2
    # The pool's own stats() stay the same numbers (one source of truth
    # for /stats' engine_pool block).
    s = p.stats()
    assert (s["hits"], s["misses"], s["evictions"]) == (2, 4, 2)


# ---------------------------------------------- the run budget (schema v4)


def test_run_budget_fields_close_and_schema_v4():
    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                    chunk_rounds=8)
    res = run(topo, cfg)
    rec = metrics_mod.run_record(cfg, topo, res)
    assert rec["schema_version"] == metrics_mod.RUN_RECORD_SCHEMA_VERSION == 5
    # The budget identity: residual is exactly the unnamed remainder.
    assert rec["residual_s"] == pytest.approx(
        res.run_s - res.dispatch_s - res.fetch_s - res.hook_s
    )
    # first_dispatch is one of the summed dispatches.
    assert 0 < res.first_dispatch_s <= res.dispatch_s
    assert res.aux_s == 0.0 and res.hook_s == 0.0  # no telemetry, no hooks
    assert len(res.chunk_log) >= 2  # several boundaries at chunk_rounds=8


def test_run_budget_hook_and_aux_buckets_fill():
    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                    chunk_rounds=8, telemetry=True)
    seen = {"chunks": 0}

    def on_chunk(rounds, state):
        seen["chunks"] += 1

    res = run(topo, cfg, on_chunk=on_chunk)
    assert seen["chunks"] >= 2
    assert res.hook_s > 0.0  # the on_chunk bracket measured something
    assert res.aux_s > 0.0  # telemetry collection measured
    assert res.aux_s <= res.fetch_s  # aux is a subset of the fetch block
    assert res.telemetry is not None and res.telemetry.rounds == res.rounds


def test_observe_run_record_and_dump(tmp_path):
    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                    chunk_rounds=8)
    res = run(topo, cfg)
    rec = metrics_mod.run_record(cfg, topo, res)
    reg = obs.Registry()
    obs.observe_run_record(rec, chunk_log=res.chunk_log, registry=reg)
    out = tmp_path / "m.prom"
    obs.dump(out, registry=reg)
    parsed = obs.parse_prometheus(out.read_text())
    assert obs.metric_value(
        parsed, "gossip_tpu_runs_total", outcome="converged") == 1
    assert obs.metric_value(
        parsed, "gossip_tpu_run_rounds_total") == res.rounds
    assert obs.metric_value(
        parsed, "gossip_tpu_run_residual_seconds") == pytest.approx(
        rec["residual_s"])
    assert obs.metric_value(
        parsed, "gossip_tpu_chunk_dispatch_seconds_count") == len(
        res.chunk_log)


def test_cli_metrics_dump_flag(tmp_path, capsys):
    from cop5615_gossip_protocol_tpu.cli import main

    out = tmp_path / "run.prom"
    rc = main(["64", "full", "gossip", "--quiet", "--chunk-rounds", "16",
               "--metrics-dump", str(out)])
    capsys.readouterr()
    assert rc == 0
    parsed = obs.parse_prometheus(out.read_text())
    assert obs.metric_value(
        parsed, "gossip_tpu_runs_total", outcome="converged") >= 1
    for g in ("run_seconds", "dispatch_seconds", "fetch_seconds",
              "first_dispatch_seconds", "residual_seconds"):
        assert obs.metric_value(parsed, f"gossip_tpu_run_{g}") is not None
    # The process-wide registry also carries the pool counters the run
    # populated.
    assert obs.metric_value(
        parsed, "gossip_tpu_engine_pool_misses_total") >= 1


def test_cli_metrics_dump_rejected_for_replica_sweeps(capsys):
    from cop5615_gossip_protocol_tpu.cli import main

    rc = main(["64", "full", "gossip", "--replicas", "2",
               "--metrics-dump", "-"])
    err = capsys.readouterr().err
    assert rc == 2 and "--metrics-dump" in err


# --------------------------- trace ids, spans, /metrics (serving plane)


def test_serving_trace_spans_metrics_and_event_join(tmp_path):
    ev_path = tmp_path / "serve_events.jsonl"
    app = ServingApp(window_s=0.05, max_lanes=8, min_lanes=1,
                     event_log=RunEventLog(ev_path))
    httpd = make_server(app, "127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        # Two concurrent same-bucket requests: distinct trace ids must
        # survive co-batching into one vmapped program.
        results = {}

        def go(i):
            results[i] = app.handle_run(
                {"schema_version": 1, "n": 32, "topology": "full",
                 "algorithm": "gossip", "seed": 100 + i}
            )

        threads = [threading.Thread(target=go, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tids = set()
        for status, resp in results.values():
            assert status == 200, resp
            sv = resp["serving"]
            assert sv["trace_id"]
            tids.add(sv["trace_id"])
            spans = sv["spans"]
            assert set(spans) == {"queue_wait_s", "batch_assemble_s",
                                  "engine_s", "demux_s"}
            # The spans partition the service wall exactly (5% is the CI
            # bar; construction makes it ~float-exact).
            assert sum(spans.values()) == pytest.approx(
                sv["service_ms"] / 1e3, rel=0.05)
            # Every per-request event carries the id.
            assert all(e["trace_id"] == sv["trace_id"]
                       for e in resp["events"])
        assert len(tids) == 2  # distinct identities per request

        # The event-log join: admitted -> batch-retired -> completed, in
        # order, for each trace id (the ISSUE 7 acceptance join).
        events = read_events(ev_path)
        for tid in tids:
            kinds = [e["event"] for e in events
                     if e.get("trace_id") == tid
                     or tid in (e.get("trace_ids") or ())]
            assert kinds.count("request-admitted") == 1, kinds
            assert kinds.count("batch-retired") == 1, kinds
            assert kinds.count("request-completed") == 1, kinds
            assert kinds.index("request-admitted") < kinds.index(
                "batch-retired") < kinds.index("request-completed")

        # GET /metrics under the live server: parseable exposition whose
        # series satisfy the /stats identities at quiescence.
        import http.client

        host, port = httpd.server_address[:2]
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type", "").startswith("text/plain")
        parsed = obs.parse_prometheus(resp.read().decode())
        conn.close()

        def mv(name):
            return obs.metric_value(parsed, f"gossip_tpu_serving_{name}")

        assert mv("received_total") == mv("admitted_total") == 2
        assert mv("completed_total") == 2 and mv("failed_total") == 0
        assert mv("received_total") == (
            mv("admitted_total") + mv("rejected_total")
            + mv("invalid_total"))
        assert mv("batched_requests_total") == (
            mv("completed_total") + mv("failed_total"))
        assert mv("service_seconds_count") == 2
        for span in ("queue_wait", "batch_assemble", "engine", "demux"):
            assert mv(f"{span}_seconds_count") == 2, span
        # The process-wide series (pool) ride the same scrape.
        assert obs.metric_value(
            parsed, "gossip_tpu_engine_pool_misses_total") >= 1
        # /stats percentiles now come from the streaming histogram —
        # present and within the documented bound of the histogram read.
        snap = app.snapshot()
        assert snap["service_ms_p99"] is not None
        assert snap["service_ms_p50"] <= snap["service_ms_p99"]
        p99 = app.stats._h_service.quantile(0.99)
        assert snap["service_ms_p99"] == pytest.approx(1e3 * p99)
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.close()


def test_admission_rejection_carries_trace_id():
    from cop5615_gossip_protocol_tpu.serving.admission import (
        AdmissionError,
        ServingStats,
    )
    from cop5615_gossip_protocol_tpu.serving.batcher import MicroBatcher

    b = MicroBatcher(stats=ServingStats(), queue_limit=1, min_lanes=1)
    # NOT started: the queue fills and the second submit is rejected.
    r1 = b.submit(SimConfig(n=32, topology="full", algorithm="gossip",
                            seed=0, engine="chunked"), False)
    assert r1.trace_id
    with pytest.raises(AdmissionError) as e:
        b.submit(SimConfig(n=32, topology="full", algorithm="gossip",
                           seed=1, engine="chunked"), False)
    assert e.value.trace_id and e.value.trace_id != r1.trace_id
    b.stop(drain=False)


# ------------------------------------------------- wallwalk bucket closure


def test_wallwalk_attribution_closure():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from benchmarks import wallwalk

    rep = wallwalk.walk(
        dict(n=64, topology="full", algorithm="gossip", seed=0,
             chunk_rounds=8, max_rounds=100_000),
        telemetry=True, checkpoint=True,
    )
    assert rep["outcome"] == "converged"
    buckets = rep["buckets"]
    assert set(buckets) == {"init", "build", "compile", "setup",
                            "dispatch", "engine", "aux", "hook",
                            "finalize", "record", "loop*", "harness*"}
    # The directly bracketed phases measured something real; hook/aux
    # exercised by the checkpoint + telemetry knobs.
    assert buckets["hook"] > 0 and buckets["aux"] > 0
    assert buckets["setup"] > 0 and buckets["finalize"] > 0
    # The acceptance pin: >= 90% of the non-engine wall lands in DIRECTLY
    # MEASURED buckets — the subtraction-defined remainders (loop*,
    # harness*) and any unattributed gap count against closure, so the
    # check fails if an unbracketed cost appears (review finding: the
    # earlier all-derived formulation was tautologically 100%).
    assert rep["closure"] >= 0.9, rep
    assert rep["closure"] < 1.0  # the remainders are real, not zeroed
    # ... and the unattributed gap is what closure says it is.
    assert rep["unattributed_s"] == pytest.approx(
        rep["total_s"] - sum(buckets.values()))
    md = wallwalk.render_md(rep)
    assert "closure" in md and "| init |" in md


# ------------------------------------------------------------ trend table


def test_trend_table_renders_and_applies_idempotently(tmp_path):
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from benchmarks import trend

    root = tmp_path
    (root / "BENCH_r01.json").write_text(json.dumps({
        "parsed": {"value": 100.0, "wall_s": 1.5, "compile_s": 2.0,
                   "vs_baseline": 10.0}}))
    (root / "BENCH_r02.json").write_text(json.dumps({
        "parsed": {"value": 200.0, "wall_s": 0.5, "compile_s": 2.5,
                   "engine_us_per_round": 50.0, "vs_baseline": 20.0}}))
    (root / "MULTICHIP_r02.json").write_text(json.dumps({"ok": True}))
    (root / "BENCH_TABLES.md").write_text("# tables\n\n## existing\nrow\n")
    rc = trend.main(["--root", str(root), "--serving", "2:1234", "--apply"])
    assert rc == 0
    text1 = (root / "BENCH_TABLES.md").read_text()
    assert trend.SECTION_HEADER in text1
    assert "| r01 | 100 |" in text1 and "1,234" in text1
    assert "## existing" in text1  # prior sections untouched
    # Idempotent: a second apply replaces, never duplicates.
    rc = trend.main(["--root", str(root), "--serving", "2:1234", "--apply"])
    assert rc == 0
    text2 = (root / "BENCH_TABLES.md").read_text()
    assert text2.count(trend.SECTION_HEADER) == 1
    assert text2 == text1


def test_trend_ceilings_apply_idempotent_and_preserves_serving(tmp_path):
    # ISSUE 15 satellite: the ceilings section has its own header and its
    # own idempotent apply, and a bare --apply (no --serving flags, no
    # --ceilings) must preserve BOTH the previously applied serving pin
    # and the previously applied ceilings section — a regen can't drop
    # the r14 serving row or the ceilings table (the PR 9
    # pin-preservation rule, extended).
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from benchmarks import trend

    root = tmp_path
    (root / "BENCH_r01.json").write_text(json.dumps({
        "parsed": {"value": 100.0, "wall_s": 1.5, "compile_s": 2.0,
                   "vs_baseline": 10.0}}))
    (root / "BENCH_TABLES.md").write_text("# tables\n\n## existing\nrow\n")
    rc = trend.main(["--root", str(root), "--serving", "1:4321",
                     "--ceilings", "--apply"])
    assert rc == 0
    text1 = (root / "BENCH_TABLES.md").read_text()
    assert trend.CEILINGS_HEADER in text1
    assert "replicated-pool2 (reduce_scatter)" in text1
    assert "replicated-pool2 (all_gather)" in text1
    assert "Host-sharded construction" in text1
    assert "4,321" in text1
    # Second apply WITH ceilings: byte-identical (the plan functions are
    # pure — same table both times).
    rc = trend.main(["--root", str(root), "--serving", "1:4321",
                     "--ceilings", "--apply"])
    assert (root / "BENCH_TABLES.md").read_text() == text1
    # Bare apply (no --serving, no --ceilings): the serving pin survives
    # via the parse-back path, the ceilings section is left untouched.
    rc = trend.main(["--root", str(root), "--apply"])
    assert rc == 0
    text3 = (root / "BENCH_TABLES.md").read_text()
    assert "4,321" in text3
    assert text3.count(trend.CEILINGS_HEADER) == 1
    assert "replicated-pool2 (reduce_scatter)" in text3
    assert "## existing" in text3
