"""The auditor audited: every checker has a fires (seeded-bad fixture)
and a clean (negative) pin, plus the baseline/report plumbing and the
full-matrix smoke (slow suite).

The ISSUE 11 tree audits clean — `python -m cop5615_gossip_protocol_tpu
.analysis` exits 0 on an EMPTY baseline (pinned here in the slow smoke) —
so the fires direction of each checker is proved against the seeded-bad
programs in tests/fixtures/analysis/ instead: a checker that silently
stops firing is a tier-1 failure, not a latent hole in CI.
"""

import importlib.util
import sys
import types
from pathlib import Path

import pytest

import jax

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from cop5615_gossip_protocol_tpu.analysis import (  # noqa: E402
    contracts,
    lint_rules,
    report,
    tags,
    trace,
    wire_specs,
)

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "analysis"


def _bad_programs():
    spec = importlib.util.spec_from_file_location(
        "analysis_bad_programs", FIXTURES / "bad_programs.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _cell(fn, args, donate=False, engine="fixture-engine"):
    return trace.TracedCell(
        engine=engine, topology="full", algorithm="gossip", n=8,
        n_devices=1, overlap=True, extras={}, fn=fn, args=args,
        donate=donate,
    )


# --- host-sync -------------------------------------------------------------


def test_host_sync_fires_on_body_callback():
    bad = _bad_programs()
    findings = contracts.check_host_sync(_cell(*bad.host_sync_chunk()))
    assert [f.rule for f in findings] == ["body-debug_callback"]
    assert findings[0].checker == "host-sync"


def test_host_sync_clean_on_plain_loop():
    bad = _bad_programs()
    assert contracts.check_host_sync(_cell(*bad.clean_chunk())) == []


# --- dtype policy ----------------------------------------------------------


def test_refill_host_sync_fires_on_callback():
    """The ISSUE 14 refill-path lint: a callback ANYWHERE in a
    chunk-boundary (refill) program fires, and the pure-select refill is
    clean."""
    bad = _bad_programs()
    findings = contracts.check_host_sync_whole(
        _cell(*bad.host_callback_refill())
    )
    assert [f.rule for f in findings] == ["refill-debug_callback"]
    assert findings[0].checker == "host-sync"
    assert contracts.check_host_sync_whole(_cell(*bad.clean_refill())) == []


def test_batch_engine_cells_trace_and_audit_clean():
    """The real continuous-batching programs (models/sweep): both
    variants captured trace-only, donated, and clean under the body and
    whole-program host-sync contracts."""
    with jax.experimental.enable_x64():
        cells = trace.trace_batch_cells("full", "gossip", 32, 2, {})
        for cell in cells:
            cell.closed_jaxpr
    assert sorted(c.info.get("variant") for c in cells) == [
        "batch-chunk", "batch-refill",
    ]
    for cell in cells:
        assert cell.donate is True
        if cell.info["variant"] == "batch-refill":
            assert contracts.check_host_sync_whole(cell) == []
        else:
            assert contracts.check_host_sync(cell) == []
            assert contracts.check_dtype_policy(cell) == []


def test_dtype_policy_fires_on_f64_promotion():
    bad = _bad_programs()
    with jax.experimental.enable_x64():
        cell = _cell(*bad.f64_promotion_chunk())
        cell.closed_jaxpr  # trace inside the x64 context
    findings = contracts.check_dtype_policy(cell)
    assert findings, "np.float64 promotion in the body must be flagged"
    assert all(f.rule.startswith("body-f64-") for f in findings)


def test_dtype_policy_clean_on_pinned_f32():
    bad = _bad_programs()
    with jax.experimental.enable_x64():
        cell = _cell(*bad.clean_f32_chunk())
        cell.closed_jaxpr
    assert contracts.check_dtype_policy(cell) == []


# --- donation --------------------------------------------------------------


def test_donation_fires_on_unaliased_carry():
    bad = _bad_programs()
    cell = _cell(*bad.unaliased_donated_chunk(), donate=True)
    findings = contracts.check_donation(cell)
    assert [f.rule for f in findings] == ["state-leaf-0"]


def test_donation_clean_on_donated_carry_through_compile():
    bad = _bad_programs()
    cell = _cell(*bad.donated_chunk(), donate=True)
    assert contracts.check_donation(cell, compile_check=True) == []


def test_donation_skips_when_not_donated():
    bad = _bad_programs()
    cell = _cell(*bad.unaliased_donated_chunk(), donate=False)
    assert contracts.check_donation(cell) == []


# --- matmul delivery (ISSUE 12) --------------------------------------------


def _matmul_cell(fn, args):
    return trace.TracedCell(
        engine="fixture-engine", topology="full", algorithm="gossip", n=32,
        n_devices=1, overlap=True, extras={"delivery": "matmul"}, fn=fn,
        args=args, donate=False,
    )


def test_matmul_contract_fires_on_scatter_fallback():
    bad = _bad_programs()
    findings = contracts.check_matmul_delivery(
        _matmul_cell(*bad.scatter_delivery_chunk())
    )
    rules = sorted(f.rule for f in findings)
    assert rules == ["no-dot-general", "scatter-scatter-add"], rules
    assert all(f.checker == "matmul-delivery" for f in findings)


def test_matmul_contract_clean_on_one_hot_dot_general():
    bad = _bad_programs()
    assert contracts.check_matmul_delivery(
        _matmul_cell(*bad.matmul_delivery_chunk())
    ) == []


def test_matmul_contract_skips_non_matmul_cells():
    # The scatter chunk is fine on any other rung — the contract only
    # binds cells that resolved delivery='matmul'.
    bad = _bad_programs()
    assert contracts.check_matmul_delivery(
        _cell(*bad.scatter_delivery_chunk())
    ) == []


def test_matmul_contract_clean_on_real_chunked_rung():
    # The real engine cell, traced through the runner's probe hook: the
    # chunked matmul round must carry dot_general and zero scatters.
    cell = trace.trace_cell(
        "chunked", "full", "gossip", 256, 1, True, {"delivery": "matmul"}
    )
    assert contracts.check_matmul_delivery(cell) == []
    # ... and the pool sibling must NOT be judged by the matmul contract.
    pool_cell = trace.trace_cell(
        "chunked", "full", "gossip", 256, 1, True, {"delivery": "pool"}
    )
    assert contracts.check_matmul_delivery(pool_cell) == []


# --- wire-spec -------------------------------------------------------------


def test_wire_spec_fires_on_double_psum(monkeypatch):
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.parallel.mesh import NODE_AXIS, make_mesh

    bad = _bad_programs()
    mod = types.ModuleType("analysis_fixture_wire_spec")
    mod.WIRE_SPEC = bad.FIXTURE_WIRE_SPEC
    monkeypatch.setitem(
        sys.modules, "analysis_fixture_wire_spec", mod
    )
    monkeypatch.setitem(
        wire_specs.SPEC_HOMES, "fixture-engine",
        "analysis_fixture_wire_spec",
    )
    mesh = make_mesh(2)
    cell = _cell(*bad.double_psum_chunk(mesh, NODE_AXIS))
    rep = trace.AuditReport(
        engine="fixture-engine", topology="full", algorithm="gossip",
        n=8, n_devices=2, overlap=True, counts=cell.counts,
    )
    cfg = SimConfig(n=8, topology="full", algorithm="gossip")
    findings = wire_specs.check_report(rep, build_topology("full", 8), cfg)
    assert [f.rule for f in findings] == ["body-psum"], findings
    assert "declared 1 psum in body, traced 2" in findings[0].detail


def test_wire_spec_clean_when_counts_match_declaration():
    # Synthetic counts built to exactly match the pool2 declaration — the
    # diff (including the strictness zeros and the mechanism column) must
    # come back empty without tracing anything.
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology

    spec = wire_specs.get_spec("pool2-sharded")
    cfg = SimConfig(n=1024, topology="full", algorithm="push-sum",
                    engine="fused", delivery="pool",
                    overlap_collectives=True)
    topo = build_topology("full", 1024)
    env, mode = wire_specs.wire_env("pool2-sharded", topo, cfg, 2)
    want = wire_specs.expected_counts(spec, env, "overlap", mode)
    counts = {
        region: {
            prim: {"count": n, "bytes": 64 * n}
            for prim, n in want[region].items() if n
        }
        for region in ("body", "setup")
    }
    rep = trace.AuditReport(
        engine="pool2-sharded", topology="full", algorithm="push-sum",
        n=1024, n_devices=2, overlap=True, counts=counts,
    )
    assert wire_specs.check_report(rep, topo, cfg) == []


def test_wire_spec_missing_declaration_is_a_finding():
    rep = trace.AuditReport(
        engine="undeclared-engine", topology="full", algorithm="gossip",
        n=8, n_devices=2, overlap=True,
        counts={"body": {}, "setup": {}},
    )
    findings = wire_specs.check_report(rep, None, None)
    assert [f.rule for f in findings] == ["no-spec"]


# --- prng tags -------------------------------------------------------------


def test_tags_fire_on_overlapping_registry():
    reg = {
        "base": {"a": (0, 100), "b": (50, 150)},
        "round": {"x": 7, "y": 7},
    }
    rules = {f.rule for f in tags.check_disjoint(reg)}
    assert rules == {"base-region-overlap", "round-tag-collision"}


def test_tags_fire_on_fixture_harvest():
    # Both callee forms (attribute and bare from-import, incl. data=
    # keyword) and both constant forms (plain and annotated) are visible.
    rules = [f.rule for f in tags.harvest_fold_ins(root=FIXTURES)]
    assert sorted(rules) == [
        "literal-tag-outside-map", "literal-tag-outside-map",
        "unregistered-tag-constant", "unregistered-tag-constant",
        "unregistered-tag-fold", "unregistered-tag-fold",
    ]


def test_tags_clean_on_real_tree():
    # The machine-verified TAG MAP (ops/faults.py docstring): pairwise
    # disjoint regions, every fold_in site classified.
    assert tags.check_tags() == []


# --- lints -----------------------------------------------------------------


def test_lint_host_conversions_fire_on_fixture():
    rules = sorted(
        f.rule for f in lint_rules.check_host_conversions(FIXTURES)
        if "bad_host" in f.where
    )
    assert rules == ["traced-int", "traced-item", "traced-np-asarray"]


def test_lint_schema_lockstep_fires_on_fixture():
    rules = sorted(
        f.rule for f in lint_rules.check_schema_lockstep(FIXTURES)
        if "bad_schema" in f.where
    )
    assert rules == [
        "schema-constant-unused", "schema-constant-unused",
        "schema-constant-unused", "schema-literal",
    ]


def test_lint_refusal_fires_on_fixture():
    # Two dead-ends fire (a static one and one whose f-string interpolates
    # DATA — data does not exempt the text around it); the third refusal
    # delegates to a computed *_support reason and must NOT fire.
    findings = lint_rules.check_refusals(FIXTURES / "bad_runner.py")
    assert [f.rule for f in findings] == [
        "refusal-dead-end", "refusal-dead-end",
    ]


def test_lint_multiprocess_refusal_fires_on_dead_end():
    # ISSUE 15: a plan function refusing a multi-process mesh without
    # naming a serving composition fires; the one that routes to the
    # chunked sharded engine must not.
    findings = lint_rules.check_multiprocess_refusals(
        FIXTURES / "bad_mp_plan"
    )
    assert [f.rule for f in findings] == ["refusal-dead-end"]
    assert "plan_bad_composition" in findings[0].where


def test_lints_clean_on_real_tree():
    assert lint_rules.run_lints() == []


# --- report / baseline -----------------------------------------------------


def test_baseline_split_and_stale_detection():
    f1 = report.Finding("c", "w", "r", "detail one")
    f2 = report.Finding("c", "w2", "r", "detail two")
    baseline = {"suppressions": [
        {"fingerprint": f1.fingerprint, "reason": "known"},
        {"fingerprint": "c::gone::r", "reason": "stale"},
    ]}
    new, suppressed, stale = report.apply_baseline([f1, f2], baseline)
    assert new == [f2]
    assert suppressed == [f1]
    assert stale == ["c::gone::r"]
    # Wording changes must not churn fingerprints.
    assert report.Finding("c", "w", "r", "reworded").fingerprint == (
        f1.fingerprint
    )


def test_committed_baseline_is_empty_and_loads():
    baseline = report.load_baseline()
    assert baseline["suppressions"] == []


def test_baseline_rejects_unjustified_suppression(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text('{"suppressions": [{"fingerprint": "a::b::c"}]}')
    with pytest.raises(ValueError, match="reason"):
        report.load_baseline(p)


def test_cli_lint_only_clean():
    from cop5615_gossip_protocol_tpu.analysis.__main__ import main

    assert main(["--lint-only", "--quiet"]) == 0


def test_cli_reduced_scope_does_not_judge_staleness(tmp_path):
    # A baselined traced-cell finding never fires in a --lint-only run;
    # that must NOT read as stale (exit 2) — only FULL runs audit the
    # scope the baseline was recorded against.
    from cop5615_gossip_protocol_tpu.analysis.__main__ import main

    p = tmp_path / "baseline.json"
    p.write_text(
        '{"suppressions": [{"fingerprint": '
        '"wire-spec::some/traced/cell::body-psum", '
        '"reason": "traced-cell suppression outside lint scope"}]}'
    )
    assert main(["--lint-only", "--quiet", "--baseline", str(p)]) == 0


# --- full matrix (slow) ----------------------------------------------------


@pytest.mark.slow
def test_full_matrix_audits_clean():
    # Every runner-ladder cell reachable on CPU, traced (never executed)
    # under x64, against an EMPTY baseline: wire-spec declarations,
    # host-sync freedom, dtype policy, donation aliasing, the TAG MAP and
    # the AST lints all hold on the committed tree.
    from cop5615_gossip_protocol_tpu.analysis import matrix

    findings = matrix.audit_matrix()
    assert findings == [], [f.fingerprint for f in findings]
