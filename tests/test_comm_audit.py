"""Comm-audit pins (benchmarks/comm_audit.py): collectives per round /
super-step, counted from the TRACED chunk program — a comm-volume
regression fails here on CPU without needing a TPU.

The tentpole pin: with the overlap schedule on (the default), the batched
halo wire is exactly ONE ppermute pair per super-step — down from one pair
per plane (compositions) / one ppermute per offset class (chunked halo
delivery) — and the verdict psum stays exactly one per super-step (it is
deferred, not duplicated). The engines' probe hook traces the real jitted
chunk, so these counts cannot drift from what runs.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from benchmarks.comm_audit import audit_engine  # noqa: E402


def test_chunked_halo_wire_counts():
    # torus3d has 10 offset classes (lattice +/-1, +/-g, +/-g^2 and their
    # wrap variants): per-class = 10 ppermutes per round, batched = 1 pair.
    for algo in ("gossip", "push-sum"):
        on = audit_engine("sharded", "torus3d", algo, 4096, 8, True)
        off = audit_engine("sharded", "torus3d", algo, 4096, 8, False)
        assert on.body_count("ppermute") == 2, on.counts
        assert off.body_count("ppermute") == 10, off.counts
        assert on.body_count("psum") == off.body_count("psum") == 1
        # Same bytes on the wire — batching changes packaging, not payload.
        assert on.body_bytes("ppermute") == off.body_bytes("ppermute")


def test_chunked_scatter_fallback_counts():
    # Non-divisible ring: no halo plan -> scatter + ONE reduce-scatter per
    # round on either schedule (wire batching does not apply).
    for ov in (True, False):
        r = audit_engine("sharded", "ring", "gossip", 1001, 8, ov)
        assert r.body_count("reduce_scatter") == 1, r.counts
        assert r.body_count("ppermute") == 0


def test_chunked_pool_roll_counts():
    # Pool-roll delivery: K=4 dynamic rolls x log2(8)+1 ppermute stages,
    # schedule-invariant (dynamic rolls cannot be statically packed) —
    # audited so a regression in the roll decomposition is visible.
    for ov in (True, False):
        r = audit_engine(
            "sharded", "full", "push-sum", 1024, 8, ov,
            {"delivery": "pool"},
        )
        assert r.body_count("ppermute") == 16, r.counts
        assert r.body_count("psum") == 1


def test_fused_sharded_batched_wire_counts():
    cfg = {"engine": "fused", "chunk_rounds": 8}
    on = audit_engine(
        "fused-sharded", "torus3d", "push-sum", 131072, 2, True, cfg
    )
    off = audit_engine(
        "fused-sharded", "torus3d", "push-sum", 131072, 2, False, cfg
    )
    # Batched: one pair for all 4 push-sum planes; serial: a pair per plane.
    assert on.body_count("ppermute") == 2, on.counts
    assert off.body_count("ppermute") == 8, off.counts
    # Verdict psum: one per super-step either way (deferred, not removed).
    assert on.body_count("psum") == off.body_count("psum") == 1
    # Per-dispatch setup: batched = one pre-loop state exchange pair + one
    # drain psum + one pair for the round-invariant disp/deg planes;
    # serial extends disp/deg per plane (max_deg+1 pairs, no drain).
    assert on.setup_count("ppermute") == 4
    assert on.setup_count("psum") == 1
    assert off.setup_count("ppermute") == 14


def test_hbm_sharded_batched_wire_counts():
    # The 2.30x offender (ISSUE 5): the HBM-streaming composition's
    # super-step must issue exactly ONE batched ppermute pair on the
    # XLA-wire fallback path (halo_dma resolves to 'ppermute' on CPU —
    # these counts ARE the fallback-path pins).
    cfg = {"engine": "fused", "chunk_rounds": 8}
    on = audit_engine(
        "hbm-sharded", "torus3d", "push-sum", 125000, 2, True, cfg
    )
    off = audit_engine(
        "hbm-sharded", "torus3d", "push-sum", 125000, 2, False, cfg
    )
    assert on.halo_mechanism() == off.halo_mechanism() == "xla-ppermute"
    assert on.body_count("ppermute") == 2, on.counts
    assert off.body_count("ppermute") == 8, off.counts
    assert on.body_count("remote_dma") == off.body_count("remote_dma") == 0
    assert on.body_count("psum") == off.body_count("psum") == 1
    assert on.setup_count("ppermute") == 2  # pre-loop exchange only
    assert on.setup_count("psum") == 1  # the drain


def test_hbm_sharded_inkernel_dma_zero_xla_halo_collectives():
    # ISSUE 9 tentpole pin: with halo_dma='on' the halo wire moves INTO
    # the Pallas kernel — the traced program carries ZERO XLA collectives
    # on the halo path (the one remaining psum is the deferred termination
    # verdict), one async remote copy per state plane per ring direction,
    # and the remote copies ship EXACTLY the bytes the batched ppermute
    # wire shipped (same payload, different transport). The probe hook
    # traces the DMA program hardware-free, so this pins the TPU path's
    # comm structure on CPU CI.
    base = {"engine": "fused", "chunk_rounds": 8}
    for algo, n_planes in (("gossip", 3), ("push-sum", 4)):
        wire = audit_engine(
            "hbm-sharded", "torus3d", algo, 125000, 2, True, base
        )
        dma = audit_engine(
            "hbm-sharded", "torus3d", algo, 125000, 2, True,
            {**base, "halo_dma": "on"},
        )
        assert dma.halo_mechanism() == "in-kernel-dma"
        assert dma.body_count("ppermute") == 0, dma.counts
        assert dma.setup_count("ppermute") == 0, dma.counts
        assert dma.body_count("all_gather") == 0
        assert dma.body_count("reduce_scatter") == 0
        # One copy per plane per ring direction, fired at super-step entry.
        assert dma.body_count("remote_dma") == 2 * n_planes, dma.counts
        # Same halo payload as the XLA wire — transport changes, bytes
        # do not.
        assert dma.body_bytes("remote_dma") == wire.body_bytes("ppermute")
        # Termination verdict: one deferred psum in the body + the drain.
        assert dma.body_count("psum") == 1
        assert dma.setup_count("psum") == 1


def test_imp_hbm_sharded_wire_counts():
    # ISSUE 10 tentpole pin: the imp x HBM x sharded super-step is ONE
    # batched halo pair (lattice classes) + ONE all_gather (the pooled
    # long-range classes' windowed send summaries) + ONE deferred verdict
    # psum — zero stragglers. The serial schedule pays per-plane wires
    # (the documented fallback), same payload bytes.
    cfg = {"engine": "fused", "delivery": "pool"}
    for algo, n_planes, n_win in (("gossip", 3, 1), ("push-sum", 4, 2)):
        on = audit_engine(
            "imp-hbm-sharded", "imp3d", algo, 27000, 2, True, cfg
        )
        off = audit_engine(
            "imp-hbm-sharded", "imp3d", algo, 27000, 2, False, cfg
        )
        assert on.halo_mechanism() == off.halo_mechanism() == "xla-ppermute"
        assert on.body_count("ppermute") == 2, on.counts
        assert off.body_count("ppermute") == 2 * n_planes, off.counts
        assert on.body_count("all_gather") == 1, on.counts
        assert off.body_count("all_gather") == n_win, off.counts
        assert on.body_count("psum") == off.body_count("psum") == 1
        assert on.body_count("remote_dma") == 0
        # Batching changes packaging, not payload.
        assert on.body_bytes("ppermute") == off.body_bytes("ppermute")
        assert on.body_bytes("all_gather") == off.body_bytes("all_gather")
        # Per-dispatch setup: pre-loop exchange pair + pre-loop gather +
        # drain psum.
        assert on.setup_count("ppermute") == 2
        assert on.setup_count("all_gather") == 1
        assert on.setup_count("psum") == 1


def test_imp_hbm_sharded_inkernel_dma_zero_xla_halo_collectives():
    # With halo_dma='on' the lattice halo moves INTO the kernel (one async
    # remote copy per state plane per ring direction, same bytes as the
    # XLA pair) while the pooled long-range wire stays the ONE all_gather
    # — the only XLA collectives left are the gather and the deferred
    # verdict psum. Traced hardware-free through the probe hook.
    cfg = {"engine": "fused", "delivery": "pool"}
    for algo, n_planes in (("gossip", 3), ("push-sum", 4)):
        wire = audit_engine(
            "imp-hbm-sharded", "imp3d", algo, 27000, 2, True, cfg
        )
        dma = audit_engine(
            "imp-hbm-sharded", "imp3d", algo, 27000, 2, True,
            {**cfg, "halo_dma": "on"},
        )
        assert dma.halo_mechanism() == "in-kernel-dma"
        assert dma.body_count("ppermute") == 0, dma.counts
        assert dma.setup_count("ppermute") == 0, dma.counts
        assert dma.body_count("remote_dma") == 2 * n_planes, dma.counts
        assert dma.body_bytes("remote_dma") == wire.body_bytes("ppermute")
        assert dma.body_count("all_gather") == 1
        assert dma.body_count("psum") == 1


def test_pool2_sharded_single_gather_counts():
    # ISSUE 10 acceptance pin: the replicated-pool2 super-step's ONLY
    # delivery wire is ONE all_gather of the compact windowed send
    # summaries (the active plane for gossip; raw s/w for push-sum,
    # batched under the overlap schedule) plus the ONE deferred verdict
    # psum — no ppermutes, no scatters, no remote DMAs, zero stragglers.
    cfg = {"engine": "fused", "delivery": "pool"}
    for algo, n_win in (("gossip", 1), ("push-sum", 2)):
        on = audit_engine(
            "pool2-sharded", "full", algo, 262144, 2, True, cfg
        )
        off = audit_engine(
            "pool2-sharded", "full", algo, 262144, 2, False, cfg
        )
        assert on.halo_mechanism() == off.halo_mechanism() == "all-gather"
        assert on.body_count("all_gather") == 1, on.counts
        assert off.body_count("all_gather") == n_win, off.counts
        assert on.body_count("psum") == off.body_count("psum") == 1
        for r in (on, off):
            assert r.body_count("ppermute") == 0
            assert r.body_count("reduce_scatter") == 0
            assert r.body_count("remote_dma") == 0
        assert on.body_bytes("all_gather") == off.body_bytes("all_gather")
        # Per-dispatch setup: the pre-loop gather + the drain psum.
        assert on.setup_count("all_gather") == 1
        assert on.setup_count("psum") == 1


def test_fused_pool_sharded_batched_gather_counts():
    cfg = {"engine": "fused", "delivery": "pool"}
    for algo, per_plane in (("gossip", 3), ("push-sum", 4)):
        on = audit_engine(
            "fused-pool-sharded", "full", algo, 131072, 2, True, cfg
        )
        off = audit_engine(
            "fused-pool-sharded", "full", algo, 131072, 2, False, cfg
        )
        assert on.body_count("all_gather") == 1, on.counts
        assert off.body_count("all_gather") == per_plane, off.counts
        # The composition's verdict is replicated in-kernel: no reduction
        # collective exists on either schedule.
        assert on.body_count("psum") == off.body_count("psum") == 0
        assert on.body_bytes("all_gather") == off.body_bytes("all_gather")
