"""Comm-audit pins: declaration <-> trace agreement per composition.

Since ISSUE 11 the expected collective counts live ONCE, as data, in each
composition's ``WIRE_SPEC`` declaration (the module that builds the chunk
also declares what it puts on the wire — analysis/wire_specs.py); these
tests trace the real jitted chunk through the probe hook
(analysis/trace.py) and assert the traced program matches the
declaration EXACTLY — every undeclared collective class must count zero,
the mechanism column must classify as declared, batching must repackage
(never change) the wire payload, and the in-kernel DMA transport must
ship exactly the bytes the XLA wire shipped.

So the historical tentpole pins still hold, but from the spec: the
batched halo wire is ONE ppermute pair per super-step, imp DMA mode
keeps ZERO XLA collectives on the halo path, replicated-pool2's gather
wire is ONE all_gather + the deferred verdict psum and its banded
reduce_scatter wire (ISSUE 15) is slots x segments reduce_scatters + one
margin ppermute volley with per-device received bytes dropping from
O(N) to O(N/P + margins). What this
file pins with literals instead is the WIRE ENVIRONMENT — the structural
quantities (offset classes, pool rolls, disp pairs, planes, windows) the
linear declarations are evaluated over — so a broken env computation
cannot conspire with a broken declaration to cancel out.

A comm-volume regression still fails here on CPU without needing a TPU.
"""

import functools
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from benchmarks.comm_audit import audit_engine  # noqa: E402

from cop5615_gossip_protocol_tpu import (  # noqa: E402
    SimConfig,
    build_topology,
)
from cop5615_gossip_protocol_tpu.analysis import wire_specs  # noqa: E402


@functools.lru_cache(maxsize=None)
def _report(engine, topo_name, algo, n, n_dev, overlap, extra_items):
    return audit_engine(
        engine, topo_name, algo, n, n_dev, overlap, dict(extra_items)
    )


def _cell(engine, topo_name, algo, n, n_dev, overlap, extra=None):
    """(report, topo, cfg, env, mode) for one cell — traces are cached, so
    the transport/schedule pair tests reuse the single-schedule traces."""
    extra = dict(extra or {})
    rep = _report(
        engine, topo_name, algo, n, n_dev, overlap,
        tuple(sorted(extra.items())),
    )
    cfg = SimConfig(
        n=n, topology=topo_name, algorithm=algo,
        overlap_collectives=overlap, **extra,
    )
    topo = build_topology(topo_name, n)
    env, mode = wire_specs.wire_env(engine, topo, cfg, n_dev)
    return rep, topo, cfg, env, mode


def _assert_agrees(engine, topo_name, algo, n, n_dev, extra=None):
    """Both schedules match the declaration; batching preserves payload.
    Returns {overlap: report} plus the serial env for extra pins."""
    spec = wire_specs.get_spec(engine)
    pair = {}
    env = mode = None
    for overlap in (True, False):
        rep, topo, cfg, env, mode = _cell(
            engine, topo_name, algo, n, n_dev, overlap, extra
        )
        findings = wire_specs.check_report(rep, topo, cfg)
        assert not findings, [f.detail for f in findings]
        pair[overlap] = rep
    byte_findings = wire_specs.check_schedule_pair(
        spec, pair[True], pair[False]
    )
    assert not byte_findings, [f.detail for f in byte_findings]
    return pair, env, mode


def test_every_audited_engine_declares_a_spec():
    # A composition cannot ship without a wire contract: every engine in
    # the audited matrix resolves to a WIRE_SPEC whose variant table is
    # non-empty and whose mechanism strings are the classifier's alphabet.
    from cop5615_gossip_protocol_tpu.analysis.matrix import AUDIT_GRID

    mechs = {"xla-ppermute", "in-kernel-dma", "all-gather",
             "reduce-scatter", "scatter", "none"}
    for engine in {g[0] for g in AUDIT_GRID}:
        spec = wire_specs.get_spec(engine)
        assert spec.engine == engine
        assert spec.variants
        for (schedule, _mode), regions in spec.variants.items():
            assert schedule in ("overlap", "serial")
            assert set(regions.body) | set(regions.setup) <= set(
                wire_specs.ALL_WIRE_PRIMS
            )
        assert set(spec.mechanism.values()) <= mechs


def test_chunked_halo_declaration_agreement():
    # torus3d has 10 offset classes (lattice +/-1, +/-g, +/-g^2 and their
    # wrap variants) — the env literal pinned HERE; the per-class/batched
    # wire counts come from the declaration.
    for algo in ("gossip", "push-sum"):
        _pair, env, mode = _assert_agrees(
            "sharded", "torus3d", algo, 4096, 8
        )
        assert mode == "halo"
        assert env["classes"] == 10


def test_chunked_scatter_fallback_agreement():
    # Non-divisible ring: no exact halo plan -> the scatter fallback mode
    # (wire batching does not apply; the declaration says so).
    _pair, _env, mode = _assert_agrees("sharded", "ring", "gossip", 1001, 8)
    assert mode == "scatter"


def test_chunked_pool_roll_agreement():
    # Pool-roll delivery: K=4 dynamic rolls x log2(8)+1 ppermute stages,
    # schedule-invariant. The roll count is the env literal pinned here.
    _pair, env, mode = _assert_agrees(
        "sharded", "full", "push-sum", 1024, 8, {"delivery": "pool"}
    )
    assert mode == "pool"
    assert env["rolls"] == 16


def test_fused_sharded_declaration_agreement():
    # Env pins: push-sum carries 4 state planes; torus3d max_deg+1 = 7
    # round-invariant disp/deg exchange pairs (the serial setup wires).
    _pair, env, _mode = _assert_agrees(
        "fused-sharded", "torus3d", "push-sum", 131072, 2,
        {"engine": "fused", "chunk_rounds": 8},
    )
    assert env["planes"] == 4
    assert env["disp_pairs"] == 7


def test_hbm_sharded_wire_declaration_agreement():
    # The 2.30x offender (ISSUE 5): the declaration says ONE batched
    # ppermute pair per super-step on the XLA-wire path; halo_dma
    # resolves to 'wire' on CPU, so these ARE the fallback-path pins.
    cfg = {"engine": "fused", "chunk_rounds": 8}
    _pair, env, mode = _assert_agrees(
        "hbm-sharded", "torus3d", "push-sum", 125000, 2, cfg
    )
    assert mode == "wire"
    assert env["planes"] == 4


def test_hbm_sharded_inkernel_dma_transport_pair():
    # ISSUE 9 tentpole, from the spec: the dma variants declare remote_dma
    # wires and NO ppermute class, so "zero XLA collectives on the halo
    # path" is the strictness rule firing, not a hand literal; and the
    # remote copies ship EXACTLY the bytes the batched ppermute wire
    # shipped (dma_bytes_match). Traced hardware-free through the probe.
    base = {"engine": "fused", "chunk_rounds": 8}
    spec = wire_specs.get_spec("hbm-sharded")
    for algo in ("gossip", "push-sum"):
        wire, *_ = _cell(
            "hbm-sharded", "torus3d", algo, 125000, 2, True, base
        )
        dma_pair, _env, mode = _assert_agrees(
            "hbm-sharded", "torus3d", algo, 125000, 2,
            {**base, "halo_dma": "on"},
        )
        assert mode == "dma"
        transport = wire_specs.check_transport_pair(
            spec, wire, dma_pair[True]
        )
        assert not transport, [f.detail for f in transport]


def test_imp_hbm_sharded_declaration_agreement():
    # ISSUE 10 tentpole, from the spec: ONE batched halo pair + ONE
    # all_gather of the windowed send summaries + ONE deferred verdict
    # psum — zero stragglers (strictness covers the rest). Env pins: the
    # push-sum cell gathers 2 send windows, gossip 1.
    cfg = {"engine": "fused", "delivery": "pool"}
    for algo, n_win in (("gossip", 1), ("push-sum", 2)):
        _pair, env, mode = _assert_agrees(
            "imp-hbm-sharded", "imp3d", algo, 27000, 2, cfg
        )
        assert mode == "wire"
        assert env["windows"] == n_win


def test_imp_hbm_sharded_inkernel_dma_transport_pair():
    # DMA transport: the lattice halo moves in-kernel with the same bytes
    # as the XLA pair, while the pooled long-range wire stays the ONE
    # all_gather — all from the (schedule, 'dma') declaration.
    cfg = {"engine": "fused", "delivery": "pool"}
    spec = wire_specs.get_spec("imp-hbm-sharded")
    for algo in ("gossip", "push-sum"):
        wire, *_ = _cell(
            "imp-hbm-sharded", "imp3d", algo, 27000, 2, True, cfg
        )
        dma_pair, _env, mode = _assert_agrees(
            "imp-hbm-sharded", "imp3d", algo, 27000, 2,
            {**cfg, "halo_dma": "on"},
        )
        assert mode == "dma"
        transport = wire_specs.check_transport_pair(
            spec, wire, dma_pair[True]
        )
        assert not transport, [f.detail for f in transport]


def test_pool2_sharded_declaration_agreement():
    # ISSUE 10 acceptance pin, from the spec: replicated-pool2's ONLY
    # delivery wire is ONE all_gather of the compact windowed send
    # summaries + the ONE deferred verdict psum; no ppermutes, no
    # scatters, no remote DMAs (strictness).
    cfg = {"engine": "fused", "delivery": "pool"}
    for algo, n_win in (("gossip", 1), ("push-sum", 2)):
        _pair, env, _mode = _assert_agrees(
            "pool2-sharded", "full", algo, 262144, 2, cfg
        )
        assert env["windows"] == n_win


def test_pool2_sharded_reduce_scatter_declaration_agreement():
    # ISSUE 15 acceptance pin, from the spec: the banded reduce_scatter
    # wire (auto on meshes wider than the pool — here 8 devices vs
    # pool_size 4) is one banded reduce_scatter PER POOL SLOT + ONE
    # margin ppermute volley + the deferred verdict psum; NO all_gather
    # anywhere (strictness), mechanism classifies reduce-scatter, serial
    # unbatches to per-window-per-slot wires with identical payloads.
    cfg = {"engine": "fused", "delivery": "pool"}
    for algo, n_win in (("gossip", 1), ("push-sum", 2)):
        pair, env, mode = _assert_agrees(
            "pool2-sharded", "full", algo, 262144, 8, cfg
        )
        assert mode == "rs"
        assert env["slots"] == 4 and env["wslots"] == 4 * n_win
        rep = pair[True]
        assert rep.halo_mechanism() == "reduce-scatter"
        assert rep.body_count("all_gather") == 0


def test_pool2_sharded_recv_bytes_drop_o_n_to_o_n_over_p():
    # The measured wire delta the band wire exists for (ISSUE 15
    # acceptance): per-device RECEIVED payload bytes drop from the gather
    # wire's O(N) full summary copy to O(N/P + margins) bands. At the
    # same cell (n=262144 -> R=2048 rows, 8 devices, pool_size 4,
    # margin 16 rows), per window: gather receives the full R+... copy,
    # the band wire P bands of (R/8 + 16) rows plus P margin rows — the
    # formulas below are exact, so a regression in either wire's payload
    # fails loudly, not as a drifting inequality.
    LANES, R, n_dev, P, ME = 128, 2048, 8, 4, 16
    rows_loc = R // n_dev
    for algo, n_win in (("gossip", 1), ("push-sum", 2)):
        rs_rep, *_ = _cell(
            "pool2-sharded", "full", algo, 262144, n_dev, True,
            {"engine": "fused", "delivery": "pool"},
        )
        ag_rep, *_ = _cell(
            "pool2-sharded", "full", algo, 262144, n_dev, True,
            {"engine": "fused", "delivery": "pool",
             "pool2_wire": "all_gather"},
        )
        ag_recv = ag_rep.body_bytes_out("all_gather")
        # Batched gather: one stacked [n_win, R, LANES] full copy (the
        # mirror-margin concat happens AFTER the collective, locally).
        assert ag_recv == n_win * R * LANES * 4
        rs_recv = (
            rs_rep.body_bytes_out("reduce_scatter")
            + rs_rep.body_bytes_out("ppermute")
        )
        assert rs_recv == n_win * P * (rows_loc + ME) * LANES * 4
        # The drop scales as P/n_dev (+ margins): ~0.53x at this smallest
        # rs-eligible cell (P=4, 8 devices), asymptoting to P/n_dev on
        # wide meshes. The exact formulas above are the hard pin; this
        # inequality documents the direction.
        assert rs_recv < ag_recv, (algo, rs_recv, ag_recv)


def test_pool2_sharded_matmul_declaration_agreement():
    # ISSUE 12 acceptance pin: the matmul tier moves the aggregation onto
    # the MXU (per-shard one-hot blend after the one all_gather) but the
    # WIRE is untouched — the SAME declaration, including the strictness
    # zeros (no ppermutes, no scatters, no remote DMAs), must hold for
    # delivery='matmul' cells.
    cfg = {"engine": "fused", "delivery": "matmul"}
    for algo in ("gossip", "push-sum"):
        _assert_agrees("pool2-sharded", "full", algo, 262144, 2, cfg)


def test_fused_pool_sharded_declaration_agreement():
    # The VMEM pool composition: one batched gather of the replicated
    # state planes (serial: one per plane), and NO reduction collective on
    # either schedule — the declaration names no psum, strictness pins it
    # to zero.
    cfg = {"engine": "fused", "delivery": "pool"}
    for algo, planes in (("gossip", 3), ("push-sum", 4)):
        _pair, env, _mode = _assert_agrees(
            "fused-pool-sharded", "full", algo, 131072, 2, cfg
        )
        assert env["planes"] == planes
