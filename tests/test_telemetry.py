"""In-program telemetry plane (ops/telemetry.py): telemetry-on runs trace
the SAME trajectories as telemetry-off (the plane observes, never
perturbs), counters agree with independently computed chunk-boundary
values, donation + speculative pipelining survive telemetry (the whole
point — the legacy trace hook disabled both), the run-event log round-trips
its schema, and the trajectory analyzer reduces real JSONL.
"""

import json

import jax
import numpy as np
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
from cop5615_gossip_protocol_tpu.models import pipeline as pipeline_mod
from cop5615_gossip_protocol_tpu.models.sweep import run_replicas
from cop5615_gossip_protocol_tpu.ops import telemetry as telemetry_mod
from cop5615_gossip_protocol_tpu.ops.telemetry import (
    COL_ACTIVE,
    COL_CONV,
    COL_DROPS,
    COL_GAP,
    COL_LIVE,
    COL_MAE,
    N_COLS,
)
from cop5615_gossip_protocol_tpu.utils import events as events_mod


def _run_pair(kind, n, **cfg_kwargs):
    """(result_on, result_off, boundary-states-on, boundary-states-off):
    the same config with and without telemetry, boundary states captured
    via the checkpoint hook for bitwise comparison."""
    topo = build_topology(kind, n, seed=cfg_kwargs.get("seed", 0))
    out = []
    for tele in (True, False):
        cfg = SimConfig(n=n, topology=kind, telemetry=tele, **cfg_kwargs)
        bounds = []

        def hook(rounds, state, bounds=bounds):
            bounds.append((rounds, jax.tree.map(np.asarray, state)))

        out.append((run(topo, cfg, on_chunk=hook), bounds))
    (res_on, b_on), (res_off, b_off) = out
    return res_on, res_off, b_on, b_off


def _assert_bitwise(res_on, res_off, b_on, b_off):
    assert res_on.rounds == res_off.rounds
    assert res_on.converged_count == res_off.converged_count
    assert res_on.outcome == res_off.outcome
    assert [r for r, _ in b_on] == [r for r, _ in b_off]
    for (_, sa), (_, sb) in zip(b_on, b_off):
        for f in sa._fields:
            np.testing.assert_array_equal(
                getattr(sa, f), getattr(sb, f), err_msg=f
            )


# ------------------------------------------------ on/off bitwise per engine


def test_chunked_scatter_on_off_bitwise():
    res_on, res_off, b_on, b_off = _run_pair(
        "full", 64, algorithm="gossip", seed=3, chunk_rounds=7,
        delivery="scatter",
    )
    _assert_bitwise(res_on, res_off, b_on, b_off)
    t = res_on.telemetry
    assert t is not None and res_off.telemetry is None
    assert t.data.shape == (res_on.rounds, N_COLS)
    assert t.data[-1][COL_CONV] == 64
    # conv is a latch: the trajectory must be monotone.
    assert (np.diff(t.data[:, COL_CONV]) >= 0).all()


def test_chunked_pushsum_pool_on_off_bitwise():
    res_on, res_off, b_on, b_off = _run_pair(
        "full", 64, algorithm="push-sum", seed=1, chunk_rounds=16,
        delivery="pool",
    )
    _assert_bitwise(res_on, res_off, b_on, b_off)
    t = res_on.telemetry
    # Final row's MAE matches the result's over the same state. The
    # telemetry column reduces in float32 in-trace; the result diagnostic
    # computes in float64 on the host (runner._finalize_result, ISSUE 9 —
    # zero finalize-time XLA compiles), so at a converged MAE sitting at
    # f32 quantization scale (~eps * true_mean per term) the two agree to
    # f32 reduction accuracy, not bit-for-bit.
    assert t.data[-1][COL_MAE] == pytest.approx(res_on.estimate_mae, rel=0.1)
    assert t.data[-1][COL_MAE] > 0
    # Fault-free run conserves mass: residual stays ~0.
    assert np.abs(t.data[:, telemetry_mod.COL_MASS]).max() < 1e-2


def test_sharded_on_off_bitwise_and_matches_single_device():
    res_on, res_off, b_on, b_off = _run_pair(
        "full", 64, algorithm="gossip", seed=3, chunk_rounds=7, n_devices=8,
    )
    _assert_bitwise(res_on, res_off, b_on, b_off)
    # Integer counters over a device-count-invariant stream: the sharded
    # counter block is bitwise the single-device one.
    single = run(
        build_topology("full", 64, seed=3),
        SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                  chunk_rounds=7, telemetry=True),
    )
    np.testing.assert_array_equal(
        res_on.telemetry.data, single.telemetry.data
    )


def test_fused_stencil_interpret_on_off_bitwise():
    kwargs = dict(algorithm="gossip", seed=0, engine="fused",
                  chunk_rounds=8, max_rounds=24)
    res_on, res_off, b_on, b_off = _run_pair("ring", 256, **kwargs)
    _assert_bitwise(res_on, res_off, b_on, b_off)
    # The in-kernel counters equal the chunked XLA engine's (integer state,
    # shared stream contract).
    chunked = run(
        build_topology("ring", 256, seed=0),
        SimConfig(n=256, topology="ring", telemetry=True,
                  **{**kwargs, "engine": "chunked"}),
    )
    np.testing.assert_array_equal(
        res_on.telemetry.data, chunked.telemetry.data
    )


def test_fused_pool_interpret_on_off_bitwise():
    kwargs = dict(algorithm="gossip", seed=1, engine="fused",
                  delivery="pool", chunk_rounds=8, max_rounds=24)
    res_on, res_off, b_on, b_off = _run_pair("full", 64, **kwargs)
    _assert_bitwise(res_on, res_off, b_on, b_off)
    chunked = run(
        build_topology("full", 64, seed=1),
        SimConfig(n=64, topology="full", telemetry=True,
                  **{**kwargs, "engine": "chunked"}),
    )
    np.testing.assert_array_equal(
        res_on.telemetry.data, chunked.telemetry.data
    )


def test_fused_pushsum_telemetry_columns_match_chunked():
    # The push-sum-specific in-kernel columns (estimate MAE, mass
    # residual) against the chunked engine: integer columns exact, float
    # columns to reassociation tolerance. Both fused families.
    for kind, delivery in (("ring", "auto"), ("full", "pool")):
        kwargs = dict(algorithm="push-sum", seed=1, engine="fused",
                      delivery=delivery, chunk_rounds=8, max_rounds=16)
        topo = build_topology(kind, 256 if kind == "ring" else 64, seed=1)
        fused = run(topo, SimConfig(n=topo.n, topology=kind, telemetry=True,
                                    **kwargs))
        chunked = run(topo, SimConfig(n=topo.n, topology=kind,
                                      telemetry=True,
                                      **{**kwargs, "engine": "chunked"}))
        tf, tc = fused.telemetry.data, chunked.telemetry.data
        for col in (COL_CONV, COL_LIVE, COL_GAP, telemetry_mod.COL_DROPS):
            np.testing.assert_array_equal(tf[:, col], tc[:, col], err_msg=kind)
        np.testing.assert_allclose(
            tf[:, COL_MAE], tc[:, COL_MAE], rtol=1e-5, atol=1e-7,
            err_msg=kind,
        )
        np.testing.assert_allclose(
            tf[:, telemetry_mod.COL_MASS], tc[:, telemetry_mod.COL_MASS],
            atol=1e-2, err_msg=kind,
        )


def test_fused_drop_counts_match_chunked():
    # The in-kernel fault-gate drop counters (use_gate branches) against
    # the chunked row_fn's recomputed gate — integer-exact, same stream.
    for kind, delivery in (("ring", "auto"), ("full", "pool")):
        kwargs = dict(algorithm="gossip", seed=0, engine="fused",
                      delivery=delivery, fault_rate=0.3, chunk_rounds=8,
                      max_rounds=16)
        topo = build_topology(kind, 256 if kind == "ring" else 64, seed=0)
        fused = run(topo, SimConfig(n=topo.n, topology=kind, telemetry=True,
                                    **kwargs))
        chunked = run(topo, SimConfig(n=topo.n, topology=kind,
                                      telemetry=True,
                                      **{**kwargs, "engine": "chunked"}))
        np.testing.assert_array_equal(
            fused.telemetry.data[:, COL_DROPS],
            chunked.telemetry.data[:, COL_DROPS], err_msg=kind,
        )
        assert fused.telemetry.data[:, COL_DROPS].sum() > 0


def test_sweep_replica0_matches_unbatched():
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                    chunk_rounds=7, telemetry=True)
    topo = build_topology("full", 64, seed=3)
    sweep = run_replicas(topo, cfg, 3, keep_states=False)
    single = run(topo, cfg)
    assert sweep.rounds[0] == single.rounds
    np.testing.assert_array_equal(
        sweep.telemetry[0].data, single.telemetry.data
    )
    for r in range(3):
        assert sweep.telemetry[r].data.shape == (sweep.rounds[r], N_COLS)
    # Telemetry does not perturb the sweep either.
    sweep_off = run_replicas(
        topo, SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                        chunk_rounds=7),
        3, keep_states=False,
    )
    assert sweep.rounds == sweep_off.rounds
    assert sweep_off.telemetry is None


# ------------------------------------------- counter-value cross-checks


def test_counters_match_legacy_hook_values():
    # The pre-telemetry --trace-convergence hook computed (conv, active) or
    # (conv, mae) at chunk boundaries with blocking host reductions.
    # Recompute those boundary values independently and check them against
    # the telemetry rows at the same rounds.
    topo = build_topology("grid2d", 256)
    for algo in ("gossip", "push-sum"):
        cfg = SimConfig(n=256, topology="grid2d", algorithm=algo,
                        chunk_rounds=32, telemetry=True)
        boundary = []

        def hook(rounds, state, boundary=boundary):
            import jax.numpy as jnp

            conv = int(jnp.sum(state.conv))
            if hasattr(state, "s"):
                w_safe = jnp.where(state.w != 0, state.w, 1)
                ratio = jnp.where(state.w != 0, state.s / w_safe, 0.0)
                err = jnp.where(
                    state.conv, jnp.abs(ratio - (topo.n - 1) / 2.0), 0.0
                )
                extra = float(jnp.sum(err)) / max(conv, 1)
            else:
                extra = int(jnp.sum(state.active))
            boundary.append((rounds, conv, extra))

        res = run(topo, cfg, on_chunk=hook)
        t = res.telemetry
        for rounds, conv, extra in boundary:
            row = t.data[rounds - 1]  # row i is the state AFTER round i+1
            assert row[COL_CONV] == conv, (algo, rounds)
            if algo == "push-sum":
                assert row[COL_MAE] == pytest.approx(extra, rel=1e-5)
            else:
                assert row[COL_ACTIVE] == extra, (algo, rounds)


def test_crash_model_columns_and_drop_counts():
    # Crash model: live_count tracks the schedule, gap is the quorum
    # predicate's distance. fault_rate=1 drops every live sender.
    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=2,
                    chunk_rounds=8, crash_schedule="3:8,6:4", quorum=0.9,
                    max_rounds=4000, telemetry=True)
    res = run(topo, cfg)
    t = res.telemetry.data
    live = t[:, COL_LIVE]
    assert live[0] == 64
    # Kills at round 3 (8 nodes) and 6 (4 nodes): live drops stepwise.
    assert live[-1] == 64 - 12
    assert (np.diff(live) <= 0).all()
    # Run ended because the quorum gap closed.
    assert res.outcome == "converged" and t[-1][COL_GAP] <= 0

    cfg_drop = SimConfig(n=64, topology="full", algorithm="gossip", seed=0,
                         chunk_rounds=8, fault_rate=0.999999999,
                         max_rounds=32, telemetry=True)
    res_drop = run(topo, cfg_drop)
    # With the gate ~always firing, every node's gate fires every round.
    assert (res_drop.telemetry.data[:, COL_DROPS] == 64).all()
    # And without faults the column is identically zero.
    assert (t[:, COL_DROPS] == 0).all()


# --------------------------------- donation + speculation stay on (pinned)


def test_telemetry_keeps_donation_and_pipeline_depth(monkeypatch):
    # The acceptance pin: with telemetry on and no hooks, the runner must
    # still hand the pipelined driver donate=True and the configured
    # speculation depth — the legacy trace hook forced both off.
    seen = {}
    orig = pipeline_mod.run_chunks

    def spy(**kw):
        seen["donate"] = kw.get("donate")
        seen["depth"] = kw.get("depth")
        seen["on_aux"] = kw.get("on_aux")
        return orig(**kw)

    monkeypatch.setattr(pipeline_mod, "run_chunks", spy)
    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="gossip",
                    chunk_rounds=7, pipeline_chunks=3, telemetry=True)
    res = run(topo, cfg)
    assert res.telemetry is not None and res.telemetry.rounds == res.rounds
    assert seen["donate"] is True
    assert seen["depth"] == 3
    assert seen["on_aux"] is not None


def test_driver_aux_is_speculative_not_blocking():
    # Driver-level pin of "no per-chunk blocking sync": with depth 2 the
    # dispatch of chunk k+1 happens BEFORE chunk k's aux is collected, and
    # on_aux composes with donate=True (unlike on_retire, which raises).
    log = []

    def dispatch(state, rnd, done, round_end):
        log.append(("dispatch", int(rnd), int(round_end)))
        return state, round_end, False, f"aux@{round_end}"

    auxes = []
    result = pipeline_mod.run_chunks(
        dispatch=dispatch, state0={}, rnd0=0, done0=False,
        start_round=0, max_rounds=40, stride=10, depth=2, donate=True,
        on_aux=lambda a, b, aux: log.append(("aux", a, b)) or auxes.append(aux),
    )
    assert result.rounds == 40
    assert auxes == ["aux@10", "aux@20", "aux@30", "aux@40"]
    # Chunk 2 was dispatched before chunk 1's aux was observed.
    assert log.index(("dispatch", 10, 20)) < log.index(("aux", 0, 10))
    # Timing splits recorded per retired chunk.
    assert len(result.chunk_log) == 4
    assert all(
        e["dispatch_s"] >= 0 and e["fetch_s"] >= 0 for e in result.chunk_log
    )


def test_driver_stall_discards_speculative_aux():
    # Aux of discarded speculative chunks is never observed: the stalled
    # boundary's aux is the last one collected.
    log = []

    def dispatch(state, rnd, done, round_end):
        return state, round_end, False, round_end

    stops = iter([False, True])
    auxes = []
    result = pipeline_mod.run_chunks(
        dispatch=dispatch, state0={}, rnd0=0, done0=False,
        start_round=0, max_rounds=1000, stride=10, depth=4,
        should_stop=lambda r, s: next(stops),
        on_aux=lambda a, b, aux: auxes.append(aux),
    )
    assert result.rounds == 20
    assert auxes == [10, 20]
    assert result.chunks_speculative > 0


def test_collector_streams_rows_per_retired_chunk():
    # The streaming hook (Collector.on_rows): each retired chunk's fresh
    # row slice arrives incrementally — a killed run's trace holds every
    # retired chunk — and the streamed concatenation equals the finalized
    # trajectory bitwise.
    streamed = []
    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                    chunk_rounds=7, telemetry=True)
    res = run(topo, cfg,
              on_telemetry=lambda start, rows: streamed.append((start, rows)))
    assert len(streamed) >= 2  # multiple chunks, delivered one by one
    starts = [s for s, _ in streamed]
    assert starts == sorted(starts) and starts[0] == 0
    np.testing.assert_array_equal(
        np.concatenate([r for _, r in streamed]), res.telemetry.data
    )


# ------------------------------------------------- tier gating + fallbacks


def test_fused_unsupported_tier_rejects_and_auto_falls_back():
    # imp3d pooled delivery selects the fused imp tier, which has no
    # counter block: engine='fused' must fail loudly...
    topo = build_topology("imp3d", 64, seed=0)
    cfg = SimConfig(n=64, topology="imp3d", algorithm="gossip",
                    delivery="pool", engine="fused", telemetry=True,
                    max_rounds=16)
    with pytest.raises(ValueError, match="telemetry"):
        run(topo, cfg)
    # ...while engine='auto' demotes to the chunked engine and still
    # produces a trajectory.
    res = run(topo, SimConfig(n=64, topology="imp3d", algorithm="gossip",
                              delivery="pool", engine="auto",
                              telemetry=True, max_rounds=16))
    assert res.telemetry is not None and res.telemetry.rounds == res.rounds


def test_sharded_fused_composition_rejects_telemetry():
    from cop5615_gossip_protocol_tpu.parallel.fused_sharded import (
        plan_fused_sharded,
    )

    topo = build_topology("ring", 1024)
    cfg = SimConfig(n=1024, topology="ring", engine="fused", n_devices=8,
                    telemetry=True)
    plan = plan_fused_sharded(topo, cfg, 8)
    assert isinstance(plan, str) and "telemetry" in plan


def test_reference_walk_rejects_telemetry():
    with pytest.raises(ValueError, match="single random walk"):
        SimConfig(n=25, topology="full", algorithm="push-sum",
                  semantics="reference", telemetry=True)


# ------------------------------------------------ event log + run record


def test_event_log_schema_roundtrip(tmp_path):
    p = tmp_path / "events.jsonl"
    log = events_mod.RunEventLog(p)
    log.emit("run-start", config={"n": 4}, population=4)
    log.emit_chunks([
        {"rounds": 8, "dispatch_s": 0.1, "fetch_s": 0.2},
        {"rounds": 16, "dispatch_s": 0.1, "fetch_s": 0.2},
    ])
    log.emit("run-end", outcome="converged", rounds=16)
    recs = events_mod.read_events(p)
    assert [r["event"] for r in recs] == [
        "run-start", "chunk-retired", "chunk-retired", "run-end",
    ]
    assert all(
        r["schema_version"] == events_mod.EVENT_SCHEMA_VERSION for r in recs
    )
    assert recs[1]["chunk"] == 0 and recs[2]["rounds"] == 16
    assert all("t_wall" in r and "t_run" in r for r in recs)
    # A NEWER schema is refused, not mis-parsed.
    with p.open("a") as f:
        f.write(json.dumps(
            {"schema_version": events_mod.EVENT_SCHEMA_VERSION + 1,
             "event": "x"}
        ) + "\n")
    with pytest.raises(ValueError, match="schema"):
        events_mod.read_events(p)


def test_cli_events_lifecycle(tmp_path, capsys):
    from cop5615_gossip_protocol_tpu.cli import main

    ev = tmp_path / "ev.jsonl"
    ck = tmp_path / "ck.npz"
    rc = main(["256", "grid2d", "gossip", "--quiet", "--chunk-rounds", "32",
               "--events", str(ev), "--checkpoint", str(ck),
               "--crash-schedule", "5:16", "--quorum", "0.9"])
    capsys.readouterr()
    assert rc == 0
    recs = events_mod.read_events(ev)
    kinds = [r["event"] for r in recs]
    assert kinds[0] == "run-start"
    assert kinds[1] == "crash-schedule-applied"
    assert "checkpoint-written" in kinds
    assert "chunk-retired" in kinds
    assert kinds[-1] == "run-end"
    end = recs[-1]
    assert end["outcome"] == "converged"
    assert end["rounds"] > 0 and end["dispatch_s"] >= 0
    chunk_rounds = [r["rounds"] for r in recs if r["event"] == "chunk-retired"]
    assert chunk_rounds == sorted(chunk_rounds)
    assert chunk_rounds[-1] == end["rounds"]


def test_run_record_schema_version():
    from cop5615_gossip_protocol_tpu.utils import metrics

    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="gossip")
    res = run(topo, cfg)
    rec = metrics.run_record(cfg, topo, res)
    assert rec["schema_version"] == metrics.RUN_RECORD_SCHEMA_VERSION
    assert "dispatch_s" in rec and "fetch_s" in rec
    assert "telemetry" not in rec and "chunk_log" not in rec
    json.dumps(rec)  # JSONL-serializable end to end


def test_append_jsonl_fsyncs_line(tmp_path):
    from cop5615_gossip_protocol_tpu.utils import metrics

    p = tmp_path / "out.jsonl"
    metrics.append_jsonl(p, {"a": 1})
    metrics.append_jsonl_many(p, [{"b": 2}, {"c": 3}])
    lines = [json.loads(x) for x in p.read_text().splitlines()]
    assert lines == [{"a": 1}, {"b": 2}, {"c": 3}]


# ---------------------------------------------------- trajectory analyzer


def test_trajectory_analyzer_on_real_trace(tmp_path):
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from benchmarks import trajectory as traj_mod
    from cop5615_gossip_protocol_tpu.utils import metrics

    topo = build_topology("grid2d", 256)
    cfg = SimConfig(n=256, topology="grid2d", algorithm="gossip",
                    telemetry=True)
    res = run(topo, cfg)
    p = tmp_path / "traj.jsonl"
    metrics.append_jsonl_many(
        p, res.telemetry.to_trace_records(cfg.algorithm)
    )
    recs = traj_mod.load_trace(p)
    a = traj_mod.analyze(recs, population=256)
    assert a["rounds_total"] == res.rounds
    assert a["converged_final"] == 256
    r2p = a["rounds_to_pct"]
    assert r2p[100] == res.rounds
    assert all(
        r2p[p1] <= r2p[p2]
        for p1, p2 in zip(traj_mod.PERCENTILES, traj_mod.PERCENTILES[1:])
    )
    md = traj_mod.section(recs, population=256)
    assert any("100%" in line for line in md)
    curve = traj_mod.ascii_curve(recs, 256, width=32, height=8)
    assert len(curve) == 10  # 8 rows + axis + label
    assert any("#" in line for line in curve)


def test_trajectory_analyzer_flags_partial_traces():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from benchmarks import trajectory as traj_mod

    # A resumed run's trace starts mid-stream with conv already nonzero:
    # percentiles crossed before the file begins must report None (the
    # true crossing round predates the trace), not the first record.
    recs = [
        {"rounds": r, "converged_count": c, "newly_converged": 0}
        for r, c in ((101, 60), (102, 80), (103, 100))
    ]
    a = traj_mod.analyze(recs, population=100)
    assert a["partial_trace"] is True
    assert a["rounds_to_pct"][50] is None  # crossed before round 101
    assert a["rounds_to_pct"][75] == 102
    assert a["rounds_to_pct"][100] == 103
    # The curve spans the trace's own window, not rounds 1..last.
    curve = traj_mod.ascii_curve(recs, 100, width=16, height=4)
    assert "101" in curve[-1] and "103" in curve[-1]
    top_row = curve[0]
    assert "#" in top_row  # 100% is reached inside the window
    # A full trace is not flagged.
    full = [{"rounds": r, "converged_count": r, "newly_converged": 1}
            for r in range(1, 11)]
    assert traj_mod.analyze(full, population=10)["partial_trace"] is False


def test_sweep_record_carries_schema_version():
    from cop5615_gossip_protocol_tpu.utils.metrics import (
        RUN_RECORD_SCHEMA_VERSION,
    )

    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=0)
    rec = run_replicas(topo, cfg, 2, keep_states=False).to_record()
    assert rec["schema_version"] == RUN_RECORD_SCHEMA_VERSION
    json.dumps(rec)


def test_resume_trajectory_starts_at_checkpoint_round(tmp_path):
    # Telemetry across checkpoint/resume: the resumed trajectory indexes
    # from the checkpoint round and concatenates with the original to the
    # full run's trajectory bitwise (gossip integer counters).
    topo = build_topology("full", 64)
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                    chunk_rounds=7, telemetry=True)
    full = run(topo, cfg)

    grabbed = {}

    def grab(rounds, state):
        if rounds <= 14 and "st" not in grabbed:
            grabbed["st"], grabbed["rounds"] = (
                jax.tree.map(np.asarray, state), rounds
            )

    run(topo, cfg, on_chunk=grab)
    import jax.numpy as jnp

    start = type(grabbed["st"])(
        *(jnp.asarray(x) for x in grabbed["st"])
    )
    resumed = run(topo, cfg, start_state=start,
                  start_round=grabbed["rounds"])
    t = resumed.telemetry
    assert t.start_round == grabbed["rounds"]
    np.testing.assert_array_equal(
        t.data, full.telemetry.data[grabbed["rounds"]:]
    )
    # to_trace_records seeds newly_converged from the checkpoint baseline.
    pre = int(np.asarray(grabbed["st"].conv).sum())
    recs = t.to_trace_records("gossip", prev_conv=pre)
    assert recs[0]["rounds"] == grabbed["rounds"] + 1
    assert recs[0]["newly_converged"] == recs[0]["converged_count"] - pre
    assert sum(r["newly_converged"] for r in recs) == 64 - pre
