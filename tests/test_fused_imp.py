"""Fused imp-pool engine (ops/fused_imp.py), interpret mode on CPU.

The engine serves imp2d/imp3d under pooled long-range sampling
(delivery='pool'), delivering along L static lattice classes + P dynamic
pool classes per round, keyed on class IDS (a pool offset colliding with a
lattice displacement must not double-deliver). Oracles mirror
tests/test_fused_stencil2.py: gossip bitwise vs the chunked imp-pool path,
push-sum on rounds/estimates, resume, collision safety, gating.
"""

import jax
import jax.numpy as jnp
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import fused_imp

# Interpret-mode Pallas oracle: bitwise engine validation that cannot
# fit the ROADMAP tier-1 wall-clock budget on a CPU-only container (the
# kernels run under the Pallas interpreter). Full-suite / TPU runs
# execute it: `pytest tests/` (no -m filter) or `pytest -m slow`.
pytestmark = pytest.mark.slow


def _cfg(n, kind, algorithm="gossip", engine="fused", **kw):
    kw.setdefault("max_rounds", 50_000)
    kw.setdefault("chunk_rounds", 32)
    kw.setdefault("delivery", "pool")
    return SimConfig(n=n, topology=kind, algorithm=algorithm,
                     engine=engine, **kw)


@pytest.mark.parametrize("kind,n", [("imp2d", 300), ("imp3d", 1000)])
def test_imp_fused_gossip_matches_chunked_bitwise(kind, n):
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology(kind, n, seed=4), _cfg(n, kind, engine=engine))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.converged and b.converged
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


def test_imp_fused_gossip_suppression_bitwise():
    n = 1000  # imp3d pop 729 — unaligned, exercises the mod-n blend
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology("imp3d", n, seed=1),
                _cfg(n, "imp3d", engine=engine, suppress_converged=True))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


@pytest.mark.parametrize("pool_size", [2, 4])
def test_imp_fused_pushsum_matches_chunked(pool_size):
    n = 1000
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology("imp3d", n, seed=2),
                _cfg(n, "imp3d", algorithm="push-sum", engine=engine,
                     pool_size=pool_size, chunk_rounds=64))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.converged and b.converged
    assert a.rounds == b.rounds
    assert abs(a.estimate_mae - b.estimate_mae) < 1e-3


def test_imp_fused_resume_midway():
    n = 1000
    cfg = _cfg(n, "imp3d", chunk_rounds=8)
    topo = build_topology("imp3d", n)
    snaps = []
    full = run(topo, cfg, on_chunk=lambda r, s: snaps.append((r, s)))
    assert len(snaps) >= 2
    r0, s0 = snaps[0]
    resumed = run(topo, cfg, start_state=jax.tree.map(jnp.asarray, s0),
                  start_round=r0)
    assert resumed.rounds == full.rounds
    assert resumed.converged_count == full.converged_count


def test_imp_fused_chunk_rounds_not_multiple_of_8():
    n = 729
    a = run(build_topology("imp3d", n), _cfg(n, "imp3d", engine="chunked"))
    b = run(build_topology("imp3d", n), _cfg(n, "imp3d", chunk_rounds=5))
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


def test_imp_fused_support_gating():
    topo = build_topology("imp3d", 729)
    assert fused_imp.imp_fused_support(topo, _cfg(729, "imp3d")) is None
    # Reference semantics: the pooled re-draw cannot express Q9.
    ref = SimConfig(n=729, topology="imp3d", algorithm="gossip",
                    semantics="reference", delivery="pool", engine="fused")
    assert "Q9" in fused_imp.imp_fused_support(
        build_topology("imp3d", 729, semantics="reference"), ref
    )
    # Non-imp topology.
    assert "not an imp" in fused_imp.imp_fused_support(
        build_topology("torus3d", 729), _cfg(729, "imp3d")
    )
    # VMEM budget: assert on the formula directly — building an 8M-node
    # imp3d just to read the reason string costs ~60 s of pure Python.
    from cop5615_gossip_protocol_tpu.ops.fused_pool import build_pool_layout

    layout = build_pool_layout(8_000_000)
    assert fused_imp._plane_bytes(
        layout.n_pad, 7, "push-sum"
    ) > fused_imp._VMEM_BUDGET


def test_imp_fused_auto_selects_chunked_on_cpu():
    # auto never runs compiled Pallas off-TPU; the chunked imp-pool path
    # must serve delivery='pool' runs transparently.
    n = 729
    r = run(build_topology("imp3d", n),
            _cfg(n, "imp3d", engine="auto", algorithm="push-sum"))
    assert r.converged
