"""Fused pool engine (ops/fused_pool.py), run in interpret mode on CPU.

Oracles mirror tests/test_fused.py's contract for the stencil engine:

- the packed choice scheme (sampling.pool_choice_packed) must equal a NumPy
  re-derivation from jax.random.bits — the stream both engines share;
- full runs must match the chunked XLA pool runner: gossip bitwise (integer
  state), push-sum on rounds/estimates (float32 both paths, same op order);
  the n=1000 cases exercise the mod-n wraparound blend over a 64k-lane
  padded tail, n=65536 the zero-pad case;
- resume from a chunk-boundary snapshot follows the original trajectory;
- mass is conserved through the doubled-plane delivery;
- eligibility gating fails loudly.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import fused_pool, sampling


def _cfg(n, algorithm="gossip", engine="fused", **kw):
    kw.setdefault("max_rounds", 60000)
    kw.setdefault("chunk_rounds", 32)
    return SimConfig(n=n, topology="full", algorithm=algorithm,
                     delivery="pool", engine=engine, **kw)


@pytest.mark.parametrize("n", [1000, 65536, 70000])
def test_pool_choice_packed_matches_manual(n):
    kr = sampling.round_key(jax.random.PRNGKey(9), 17)
    K = 4
    got = np.asarray(sampling.pool_choice_packed(kr, n, K))
    rows = sampling.pool_rows(n)
    words = np.asarray(
        jax.random.bits(kr, (rows // sampling.POOL_PACK, 128), jnp.uint32)
    )
    idx = np.arange(n)
    row, lane = idx // 128, idx % 128
    want = (
        words[row // sampling.POOL_PACK, lane]
        >> (sampling.POOL_CHOICE_BITS * (row % sampling.POOL_PACK))
    ) & (K - 1)
    assert (got == want).all()


def test_pool_choice_packed_wide_fallback():
    # pool_size > 16 exceeds the 4-bit packing; the fallback draws full
    # words (a valid stream of its own) and the fused engine refuses it.
    kr = sampling.round_key(jax.random.PRNGKey(0), 0)
    choice = np.asarray(sampling.pool_choice_packed(kr, 500, 32))
    assert choice.shape == (500,) and (choice < 32).all() and (choice >= 0).all()
    assert np.unique(choice).size > 16
    topo = build_topology("full", 500)
    assert "packed-choice" in fused_pool.pool_fused_support(
        topo, _cfg(500, pool_size=32)
    )


@pytest.mark.slow  # interpret-mode run pair; see tier-1 budget note in test_fused.py
@pytest.mark.parametrize("n", [1000, 65536])
def test_fused_pool_gossip_matches_chunked_bitwise(n):
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(build_topology("full", n), _cfg(n, engine=engine))
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.converged and b.converged
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


@pytest.mark.slow  # interpret-mode run pair; see tier-1 budget note in test_fused.py
def test_fused_pool_gossip_two_tiles():
    # rows = 1024 -> two in-kernel tiles; cross-tile gathers exercised.
    n = 70000
    a = run(build_topology("full", n), _cfg(n, engine="chunked"))
    b = run(build_topology("full", n), _cfg(n, engine="fused"))
    assert a.converged and b.converged
    assert a.rounds == b.rounds and a.converged_count == b.converged_count


@pytest.mark.slow  # interpret-mode run pair; see tier-1 budget note in test_fused.py
@pytest.mark.parametrize("pool_size", [2, 4, 16])
def test_fused_pool_pushsum_matches_chunked(pool_size):
    n = 1000
    results = {}
    for engine in ["chunked", "fused"]:
        r = run(
            build_topology("full", n),
            _cfg(n, algorithm="push-sum", engine=engine, pool_size=pool_size,
                 chunk_rounds=64),
        )
        results[engine] = r
    a, b = results["chunked"], results["fused"]
    assert a.converged and b.converged
    # Same f32 op order both paths => rounds agree exactly at this scale.
    assert a.rounds == b.rounds
    assert abs(a.estimate_mae - b.estimate_mae) < 1e-3


@pytest.mark.slow  # interpret-mode run pair; see tier-1 budget note in test_fused.py
def test_fused_pool_gossip_suppression_reference_mode():
    # Reference semantics on full: Q1 population n+1, Q2 11th receipt, C13
    # leader self-count, converged-target suppression via the doubled conv
    # plane (the dictionary probe, program.fs:92).
    n = 512
    results = {}
    for engine in ["chunked", "fused"]:
        cfg = SimConfig(n=n, topology="full", algorithm="gossip",
                        semantics="reference", delivery="pool", engine=engine,
                        max_rounds=20000, chunk_rounds=32)
        results[engine] = run(
            build_topology("full", n, semantics="reference"), cfg
        )
    a, b = results["chunked"], results["fused"]
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count
    assert b.converged


@pytest.mark.slow  # interpret-mode run pair; see tier-1 budget note in test_fused.py
def test_fused_pool_mass_conservation():
    n = 1000
    seen = []
    run(build_topology("full", n),
        _cfg(n, algorithm="push-sum", chunk_rounds=16),
        on_chunk=lambda r, st: seen.append(
            (float(jnp.sum(st.s)), float(jnp.sum(st.w)))
        ))
    true_s = n * (n - 1) / 2.0
    for s_tot, w_tot in seen:
        assert abs(s_tot - true_s) / true_s < 1e-4
        assert abs(w_tot - n) / n < 1e-5


def test_fused_pool_drop_gate_matches_chunked_bitwise():
    # Acceptance pin: --fault-rate accepted by the fused pool engine, the
    # in-kernel regenerated threefry gate matching ops/sampling.send_gate
    # word for word — integer gossip state, so round + converged-count
    # equality is bitwise trajectory equality.
    n = 1000
    results = {}
    for engine in ["chunked", "fused"]:
        results[engine] = run(
            build_topology("full", n), _cfg(n, engine=engine, fault_rate=0.2)
        )
    a, b = results["chunked"], results["fused"]
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count
    assert a.converged and b.converged


@pytest.mark.slow  # interpret-mode run pair; see tier-1 budget note in test_fused.py
def test_fused_pool_crash_quorum_matches_chunked():
    # Crash plane + quorum verdict in-kernel (ops/faults.py): the fused
    # pool run must stop on the same round as the chunked engine, via
    # quorum — 150 dead nodes make the legacy full-count target
    # permanently unreachable.
    n = 512
    results = {}
    for engine in ["chunked", "fused"]:
        results[engine] = run(
            build_topology("full", n),
            _cfg(n, algorithm="push-sum", engine=engine, fault_rate=0.3,
                 crash_schedule="3:100,6:50", quorum=0.95, max_rounds=8000),
        )
    a, b = results["chunked"], results["fused"]
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count
    assert a.outcome == b.outcome == "converged"
    assert a.converged_count < n  # quorum, not the legacy target


@pytest.mark.slow  # interpret-mode run pair; see tier-1 budget note in test_fused.py
def test_fused_pool_resume_midway():
    n = 1000
    cfg = _cfg(n, chunk_rounds=8)
    topo = build_topology("full", n)
    snaps = []
    full = run(topo, cfg, on_chunk=lambda r, s: snaps.append((r, s)))
    assert len(snaps) >= 2
    r0, s0 = snaps[0]
    resumed = run(topo, cfg, start_state=jax.tree.map(jnp.asarray, s0),
                  start_round=r0)
    assert resumed.rounds == full.rounds
    assert resumed.converged_count == full.converged_count


@pytest.mark.slow  # interpret-mode run pair; see tier-1 budget note in test_fused.py
@pytest.mark.parametrize("chunk_rounds", [5, 100])
def test_fused_pool_chunk_rounds_not_multiple_of_8(chunk_rounds):
    # SMEM key/offset blocks pad to 8-round multiples with zeros; padded
    # grid steps must never execute (same regression class as the stencil
    # engine's zero-key bug, tests/test_fused.py).
    n = 1000
    a = run(build_topology("full", n), _cfg(n, engine="chunked"))
    b = run(build_topology("full", n),
            _cfg(n, engine="fused", chunk_rounds=chunk_rounds))
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


def test_pool_fused_support_gating():
    topo = build_topology("full", 1000)
    # float64
    assert "float32" in fused_pool.pool_fused_support(
        topo, _cfg(1000, dtype="float64", algorithm="push-sum")
    )
    # drop-gate and crash fault models run IN-KERNEL (this PR's failure
    # subsystem, ops/faults.py) — the engine must accept them...
    assert fused_pool.pool_fused_support(topo, _cfg(1000, fault_rate=0.1)) is None
    assert fused_pool.pool_fused_support(
        topo, _cfg(1000, crash_rate=0.01, quorum=0.9)
    ) is None
    # ...while dup/delay restructure delivery itself and stay chunked-only.
    assert "chunked" in fused_pool.pool_fused_support(
        topo, _cfg(1000, dup_rate=0.1)
    )
    # population cap
    big = build_topology("full", fused_pool.MAX_POOL_NODES + 1)
    assert "exceeds" in fused_pool.pool_fused_support(
        big, _cfg(fused_pool.MAX_POOL_NODES + 1)
    )
    # explicit topology
    line = build_topology("line", 100)
    cfg_line = SimConfig(n=100, topology="full", delivery="pool")
    assert "full topology only" in fused_pool.pool_fused_support(line, cfg_line)
