"""Measured-cost plan autotuner pins (ISSUE 17).

The contract under test, in three legs:

- **Parity**: with the COMMITTED calibration (analysis/calibration.json),
  ``plan='auto'`` reproduces the hand ladder's choice on every
  BENCH/serving cell in ``cost.AUTOTUNE_CELLS`` — every kind, both
  algorithms, every delivery/wire tier, two sizes where the tier scales.
  The hand rules stay the oracle; the model must agree, not replace.
- **Fires direction**: the model is a real decision procedure, not a
  replay — a seeded-BAD calibration (near-free VPU ops, ruinous HBM
  bytes) must FLIP a known choice. A cost model that cannot change its
  answer under different measurements is dead code.
- **Shared wire formula** (satellite): comm_audit's recv-bytes reduction
  is ONE library call (``jaxpr_walk.body_recv_bytes`` over
  ``WIRE_PRIMS``) consumed by both the audit table and the cost model's
  wire term — pinned value-equal against the open-coded sum on the
  PR 15 replicated-pool2 n=2^18 / 8-device cell.
"""

import dataclasses
import functools
import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax  # noqa: E402

from benchmarks.comm_audit import audit_engine  # noqa: E402

from cop5615_gossip_protocol_tpu import (  # noqa: E402
    SimConfig,
    build_topology,
)
from cop5615_gossip_protocol_tpu.analysis import cost, jaxpr_walk  # noqa: E402
from cop5615_gossip_protocol_tpu.models import runner  # noqa: E402
from cop5615_gossip_protocol_tpu.serving import keys  # noqa: E402

GOOD_FLOORS = {
    "dispatch_us": 50.0,
    "hbm_byte_ns": 0.01,
    "vpu_op_ns": 1000.0,
    "mxu_flop_ns": 0.01,
    "addressing_ns_per_elem": 5.0,
    "wire_byte_ns": 0.02,
}


def _cal(floors) -> dict:
    return {"schema": cost.CALIBRATION_SCHEMA, "floors": dict(floors)}


@functools.lru_cache(maxsize=None)
def _cell(kind, algo, n, overrides_items):
    cfg = SimConfig(n=n, topology=kind, algorithm=algo,
                    **dict(overrides_items))
    topo = build_topology(kind, n)
    return topo, cfg


def _cells():
    for kind, algo, n, overrides in cost.AUTOTUNE_CELLS:
        n_dev = overrides.get("n_devices") or 1
        if n_dev > len(jax.devices()):
            continue
        yield kind, algo, n, overrides


# ---------------------------------------------------------------------------
# Calibration file: schema, validation, committed artifact.


def test_committed_calibration_loads_and_validates():
    cal = cost.load_calibration()
    cost.validate_calibration(cal)
    assert cal["schema"] == cost.CALIBRATION_SCHEMA
    assert set(cost.FLOOR_KEYS) <= set(cal["floors"])


def test_calibration_schema_mismatch_rejected():
    with pytest.raises(ValueError, match="schema"):
        cost.validate_calibration({"schema": 99, "floors": GOOD_FLOORS})


def test_calibration_missing_floor_rejected():
    floors = dict(GOOD_FLOORS)
    del floors["vpu_op_ns"]
    with pytest.raises(ValueError, match="vpu_op_ns"):
        cost.validate_calibration(_cal(floors))


@pytest.mark.parametrize("bad", [0.0, -1.0, float("nan"), float("inf")])
def test_calibration_nonpositive_floor_rejected(bad):
    floors = dict(GOOD_FLOORS, hbm_byte_ns=bad)
    with pytest.raises(ValueError, match="hbm_byte_ns"):
        cost.validate_calibration(_cal(floors))


def test_calibration_file_on_disk_is_current_schema():
    raw = json.loads(cost.CALIBRATION_PATH.read_text())
    assert raw["schema"] == cost.CALIBRATION_SCHEMA
    # Provenance must say where it came from, so a stale artifact is
    # diagnosable from the file alone.
    assert "generated_by" in raw.get("provenance", {})


# ---------------------------------------------------------------------------
# Config knob.


def test_plan_knob_validates():
    with pytest.raises(ValueError, match="unknown plan"):
        SimConfig(n=64, topology="line", plan="bogus")


def test_plan_auto_refuses_reference_semantics():
    with pytest.raises(ValueError, match="reference"):
        SimConfig(n=64, topology="line", plan="auto",
                  semantics="reference")


# ---------------------------------------------------------------------------
# Tentpole leg 1: the autotuner reproduces the hand ladder on every
# BENCH/serving cell (all kinds x algorithms x delivery/wire tiers, two
# sizes) with the committed calibration.


@pytest.mark.parametrize(
    "kind,algo,n,overrides",
    list(_cells()),
    ids=lambda v: str(v).replace(" ", "") if not isinstance(v, dict)
    else ",".join(f"{k}={v[k]}" for k in sorted(v)) or "defaults",
)
def test_parity_model_reproduces_hand_ladder(kind, algo, n, overrides):
    topo, cfg = _cell(kind, algo, n, tuple(sorted(overrides.items())))
    decision = cost.choose(topo, cfg)
    assert decision.winner.name == cost.hand_choice(topo, cfg)
    assert decision.predicted_us_per_round > 0


def test_parity_sweep_covers_tiers_and_two_sizes():
    """The sweep itself must stay representative: every topology kind,
    both algorithms, every delivery tier, sharded cells on both wire
    outcomes, and at least one tier at two sizes."""
    cells = list(cost.AUTOTUNE_CELLS)
    kinds = {c[0] for c in cells}
    assert {"line", "ring", "grid2d", "grid3d", "torus3d", "full",
            "imp2d", "imp3d"} <= kinds
    assert {c[1] for c in cells} == {"gossip", "push-sum"}
    deliveries = {c[3].get("delivery", "auto") for c in cells}
    assert {"auto", "stencil", "pool", "matmul", "scatter"} <= deliveries
    by_tier = {}
    for kind, algo, n, ov in cells:
        by_tier.setdefault((kind, ov.get("delivery", "auto")), set()).add(n)
    assert any(len(ns) >= 2 for ns in by_tier.values())
    assert any(c[3].get("n_devices") for c in cells)


def test_hand_oracle_matches_executed_fused_variant():
    """The oracle's fused:{variant} names are the DISPATCH's variants,
    not a parallel taxonomy: probe the real runner on the fused-pinned
    single-device cells and compare."""
    for kind, algo, n, overrides in _cells():
        if overrides.get("engine") != "fused" or "n_devices" in overrides:
            continue
        topo, cfg = _cell(kind, algo, n, tuple(sorted(overrides.items())))
        seen = {}

        def probe(fn, args, donate=None, **info):
            seen.update(info)
            return "probed"

        assert runner.run(topo, cfg, probe=probe) == "probed"
        assert cost.hand_choice(topo, cfg) == f"fused:{seen['variant']}"


def test_pool2_wire_choice_flips_with_mesh_size():
    """The wire term is measured, not assumed: the same n=2^18 matmul
    request resolves all_gather at 2 devices (every band exceeds the
    full copy) and reduce_scatter at 8 (O(N/P + margins) wins) — and the
    model's per-candidate wire costs order accordingly."""
    picks = {}
    for n_dev in (2, 8):
        topo, cfg = _cell(
            "full", "push-sum", 262_144,
            (("delivery", "matmul"), ("engine", "fused"),
             ("n_devices", n_dev)),
        )
        decision = cost.choose(topo, cfg)
        picks[n_dev] = decision.winner.name
        wires = {s.candidate.name: s.wire_us for s in decision.scores}
        assert set(wires) == {"pool2-sharded:all_gather",
                              "pool2-sharded:reduce_scatter"}
        cheaper = min(wires, key=wires.get)
        assert decision.winner.name == cheaper
    assert picks[2] == "pool2-sharded:all_gather"
    assert picks[8] == "pool2-sharded:reduce_scatter"


def test_no_candidate_raises_with_refusals():
    # Sharded matmul on the chunked engine: the hand dispatch refuses,
    # so the model must refuse too — with the reasons, not an empty
    # table.
    topo, cfg = _cell(
        "full", "push-sum", 262_144,
        (("delivery", "matmul"), ("engine", "chunked"),
         ("n_devices", 2)),
    )
    with pytest.raises(ValueError, match="no legal candidate"):
        cost.choose(topo, cfg, _cal(GOOD_FLOORS))


# ---------------------------------------------------------------------------
# Tentpole leg 2: the model FIRES in the right direction — a seeded-bad
# calibration flips a known choice.


def test_bad_calibration_flips_known_choice():
    topo, cfg = _cell("full", "push-sum", 4_096,
                      (("delivery", "pool"),))
    good = cost.choose(topo, cfg)  # committed calibration
    assert good.winner.name == "chunked" == cost.hand_choice(topo, cfg)

    # A host where VPU ops are near-free and HBM/addressing traffic is
    # ruinous: the fused pool kernel (pure VPU form) must now beat the
    # chunked engine (HBM + addressing form).
    bad = _cal(dict(GOOD_FLOORS, vpu_op_ns=1e-6, hbm_byte_ns=1e3,
                    addressing_ns_per_elem=1e3))
    flipped = cost.choose(topo, cfg, bad)
    assert flipped.winner.name == "fused:pool"
    assert {s.candidate.name for s in flipped.scores} == \
        {s.candidate.name for s in good.scores}


def test_decision_is_deterministic_for_fixed_calibration():
    topo, cfg = _cell("full", "push-sum", 4_096,
                      (("delivery", "pool"),))
    cal = _cal(GOOD_FLOORS)
    a = cost.choose(topo, cfg, cal).event_record()
    b = cost.choose(topo, cfg, cal).event_record()
    assert a == b


# ---------------------------------------------------------------------------
# Runner integration: plan='auto' resolves through the public entry,
# reports the ranked table as a structured event, and executes.


def test_runner_plan_auto_emits_plan_chosen_event():
    topo, cfg = _cell("line", "gossip", 64, (("plan", "auto"),))
    events = []

    def on_event(name, **record):
        events.append((name, record))

    def probe(fn, args, donate=None, **info):
        return "probed"

    assert runner.run(topo, cfg, probe=probe, on_event=on_event) == "probed"
    chosen = [r for nm, r in events if nm == "plan-chosen"]
    assert len(chosen) == 1
    rec = chosen[0]
    assert rec["winner"] == "chunked"
    assert rec["predicted_us_per_round"] > 0
    names = [c["plan"] for c in rec["candidates"]]
    assert names[0] == "chunked" and "fused:stencil" in names
    for c in rec["candidates"]:
        assert set(c) >= {"plan", "compute_us", "wire_us", "dispatch_us",
                          "total_us"}


def test_runner_plan_auto_executes_end_to_end():
    topo, cfg = _cell(
        "line", "gossip", 64,
        (("max_rounds", 600), ("plan", "auto"), ("seed", 0)),
    )
    hand_cfg = dataclasses.replace(cfg, plan="hand")
    auto = runner.run(topo, cfg)
    hand = runner.run(topo, hand_cfg)
    # Same winner => identical simulation, round for round.
    assert auto.rounds == hand.rounds
    assert auto.outcome == hand.outcome


def test_serve_bucket_key_pins_resolved_plan():
    topo, cfg = _cell("line", "gossip", 64, (("plan", "auto"),))
    label = keys.resolved_plan_label(cfg, topo)
    assert label == cost.choose(topo, cfg).winner.name == "chunked"
    assert ("plan", "chunked") in keys.serve_bucket_key(cfg, topo)
    hand_cfg = dataclasses.replace(cfg, plan="hand")
    assert ("plan", "hand") in keys.serve_bucket_key(hand_cfg, topo)


# ---------------------------------------------------------------------------
# Satellite: ONE recv-bytes formula, shared by the audit table and the
# cost model's wire term — pinned on the PR 15 n=2^18 / 8-device cell.


def test_recv_bytes_library_matches_table_formula():
    rep = audit_engine(
        "pool2-sharded", "full", "push-sum", 262_144, 8, True,
        {"engine": "fused", "delivery": "pool"},
    )
    body = rep.counts.get("body", {})
    open_coded_recv = sum(
        body.get(p, {}).get("bytes_out", 0) for p in jaxpr_walk.WIRE_PRIMS
    )
    open_coded_wire = sum(
        body.get(p, {}).get("bytes", 0) for p in jaxpr_walk.WIRE_PRIMS
    )
    assert jaxpr_walk.body_recv_bytes(rep.counts) == open_coded_recv > 0
    assert jaxpr_walk.body_wire_bytes(rep.counts) == open_coded_wire > 0
    # The banded reduce_scatter wire's signature quantity survives the
    # refactor: per-device received bytes stay BELOW the full-copy
    # gather (bytes ships the payload, bytes_out what one device keeps).
    assert jaxpr_walk.body_recv_bytes(rep.counts) < \
        jaxpr_walk.body_wire_bytes(rep.counts)


def test_wire_prims_exclude_psum():
    # psum is deliberately NOT a wire prim: it has its own table column,
    # and folding it in would double-count the verdict reduction.
    assert "psum" not in jaxpr_walk.WIRE_PRIMS


# ---------------------------------------------------------------------------
# Ranked-table artifact: deterministic render, skips are explicit.


def test_render_plan_table_deterministic_and_all_agree():
    cal = cost.load_calibration()
    lines_a = cost.render_plan_table(cal)
    lines_b = cost.render_plan_table(cal)
    assert lines_a == lines_b
    assert not any("**NO**" in ln for ln in lines_a)
    # Cells the host cannot trace are SKIPPED loudly, never dropped:
    # every AUTOTUNE_CELLS row appears in the summary.
    summary = "\n".join(lines_a)
    for kind, algo, n, ov in cost.AUTOTUNE_CELLS:
        assert cost.cell_label(kind, algo, n, ov) in summary
