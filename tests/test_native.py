"""Native reference simulator (native/refsim.cpp via ctypes).

The C++ engine is the reference-semantics oracle; these tests pin its
determinism, its quirk replication (Q1/Q2/Q5/Q6), and that its topology
builders agree with the Python ones in ops/topology.py.
"""

import numpy as np
import pytest

from cop5615_gossip_protocol_tpu import native
from cop5615_gossip_protocol_tpu.ops import topology as topo_mod


@pytest.fixture(scope="module", autouse=True)
def _built():
    native.refsim_build()


# ---------------------------------------------------------------------------
# Convergence + determinism


@pytest.mark.parametrize("topology", ["line", "2d", "full", "imp3d"])
@pytest.mark.parametrize("algorithm", ["gossip", "push-sum"])
def test_converges(topology, algorithm):
    r = native.refsim_run(100, topology, algorithm, seed=3)
    assert r.ok
    assert r.converged >= r.target
    assert r.events > 0
    assert r.wall_ms >= 0.0


def test_deterministic_under_seed():
    a = native.refsim_run(200, "line", "gossip", seed=11)
    b = native.refsim_run(200, "line", "gossip", seed=11)
    assert (a.events, a.leader, a.converged) == (b.events, b.leader, b.converged)
    c = native.refsim_run(200, "line", "gossip", seed=12)
    # Different seed → different leader or trajectory (overwhelmingly likely).
    assert (c.events, c.leader) != (a.events, a.leader)


def test_pushsum_is_a_single_walk():
    # Reference push-sum keeps exactly one message in flight (SURVEY.md §3.3):
    # the kickoff enqueues one ComputePushSum and every receipt enqueues at
    # most one more, so peak mailbox depth is exactly 1; gossip floods.
    ps = native.refsim_run(100, "full", "push-sum", seed=0)
    g = native.refsim_run(100, "full", "gossip", seed=0)
    assert ps.ok and g.ok
    assert ps.max_queue == 1
    assert g.max_queue > 1


# ---------------------------------------------------------------------------
# Quirk replication


def test_q1_population_off_by_one():
    r = native.refsim_run(100, "line", "gossip", seed=0)
    assert r.population == 101  # nodes+1 spawned (program.fs:152-154)
    assert r.target == 100  # parent waits for nodes (program.fs:178)


def test_q6_ref2d_rounds_up_to_square():
    r = native.refsim_run(10, "2d", "gossip", seed=0)
    assert r.target == 16  # ceil(sqrt 10)^2
    assert r.population == 17


def test_q2_gossip_needs_eleven_receipts():
    # On a 2-node-ish line (n=1 → population 2, target 1): the leader and the
    # extra actor bounce the rumor; convergence needs 11 receipts at one node,
    # so at least 11 Call events are processed before ok.
    r = native.refsim_run(1, "line", "gossip", seed=0)
    assert r.ok
    assert r.events >= 11


def test_imp3d_rounding_matches_reference_rule():
    # C3: floor(1000**0.33334)^3 = 1000 exactly (10^3); target == rounded.
    r = native.refsim_run(1000, "imp3d", "push-sum", seed=1)
    assert r.target == 1000
    assert r.population == 1001


# ---------------------------------------------------------------------------
# Topology cross-validation against the Python builders


@pytest.mark.parametrize("n", [1, 2, 17, 100])
def test_line_matches_python_builder(n):
    pop, target, deg, nbrs = native.refsim_topology(n, "line")
    py = topo_mod.build_line(n, reference=True)
    assert (pop, target) == (py.n, py.target_count)
    np.testing.assert_array_equal(deg, py.degree)
    np.testing.assert_array_equal(nbrs[:, : py.max_deg], py.neighbors)


@pytest.mark.parametrize("n", [5, 10, 100])
def test_ref2d_matches_python_builder(n):
    pop, target, deg, nbrs = native.refsim_topology(n, "2d")
    py = topo_mod.build_ref2d(n, reference=True)
    assert (pop, target) == (py.n, py.target_count)
    np.testing.assert_array_equal(deg, py.degree)
    np.testing.assert_array_equal(nbrs[:, : py.max_deg], py.neighbors)


def test_full_is_implicit_both_sides():
    pop, target, deg, nbrs = native.refsim_topology(50, "full")
    py = topo_mod.build_full(50, reference=True)
    assert (pop, target) == (py.n, py.target_count)
    assert deg is None and nbrs is None and py.implicit


def test_imp3d_structure_matches_reference_rules():
    # RNG streams differ (C++ mt19937 vs numpy PCG), so compare structure,
    # not edges: population/target, orphan placement, and degree bounds.
    n = 500
    pop, target, deg, nbrs = native.refsim_topology(n, "imp3d", seed=4)
    py = topo_mod.build_imp3d(n, seed=4, reference=True)
    assert (pop, target) == (py.n, py.target_count)
    # Same orphan set: lattice-covered nodes have degree >= 1 (grid + extra),
    # orphans exactly 0 — positions depend only on the deterministic rounding.
    np.testing.assert_array_equal(deg == 0, py.degree == 0)
    # Lattice degree 6 max + 1 extra.
    assert deg.max() <= 7 and py.degree.max() <= 7
    # Q9: extra edges never point at node target-1.
    md = nbrs.shape[1]
    cols = np.arange(md)[None, :]
    live = cols < deg[:, None]
    assert nbrs[live].max() < target


# ---------------------------------------------------------------------------
# Reference-format CLI binary (optional artifact, built on demand in bench)


def test_event_budget_reports_nonconvergence():
    r = native.refsim_run(500, "line", "gossip", seed=0, max_events=10)
    assert not r.ok
    assert r.events == 10
