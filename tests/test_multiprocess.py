"""True multi-process distributed runs (jax.distributed over two OS
processes, gloo CPU collectives) — the multi-host story executed for real,
not just on a single-process virtual mesh.

The reference's only nod at distribution is an unused Akka.Cluster package
reference (project3.fsproj:13-15, never configured — SURVEY.md C14). Here
two processes each host half the global device mesh and run the SAME
shard_map collective program via the public CLI (`--coordinator
--num-processes --process-id`); the per-round halo ppermutes and the psum
convergence predicate cross the process boundary. The oracle is the
single-process 8-virtual-device run: gossip state is integer, and the
random stream is device-count- and process-count-invariant by construction
(ops/sampling.py), so rounds and converged counts must match exactly.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run

REPO = Path(__file__).resolve().parents[1]

# Two-OS-process jax.distributed runs: minutes of subprocess spawns on a
# capable runtime, and pure spawn overhead where the CPU backend lacks
# multiprocess collectives — outside the tier-1 budget either way.
pytestmark = pytest.mark.slow

# Older jaxlib CPU clients have no cross-process collectives at all (no
# gloo); the child dies with exactly this XLA error. An explicit skip gate
# keeps the suite honest on such runtimes — any OTHER child failure still
# fails the test.
_NO_CPU_MULTIPROCESS = "aren't implemented on the CPU backend"


def _skip_if_unsupported(logs: list[str]) -> None:
    if any(_NO_CPU_MULTIPROCESS in log for log in logs):
        pytest.skip(
            "this jaxlib's CPU backend has no multiprocess collectives "
            f"({_NO_CPU_MULTIPROCESS!r})"
        )


def _spawn(pid: int, port: int, args: list[str], jsonl: Path):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "JAX_PLATFORMS", "XLA_FLAGS")}
    # A clean JAX env: repo importable, no remote-TPU site hook, CPU only.
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [
        sys.executable, "-m", "cop5615_gossip_protocol_tpu", *args,
        "--platform", "cpu", "--devices", "8",
        "--coordinator", f"127.0.0.1:{port}",
        "--num-processes", "2", "--process-id", str(pid),
        "--jsonl", str(jsonl),
    ]
    return subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def test_two_process_sharded_matches_single_process(tmp_path):
    n = 4096  # 16^3 torus: halo-exchange delivery, ppermutes cross processes
    ref = run(
        build_topology("torus3d", n),
        SimConfig(n=n, topology="torus3d", algorithm="gossip", n_devices=8),
    )
    assert ref.converged

    port = 21000 + os.getpid() % 9000
    outs = [tmp_path / f"rec{pid}.jsonl" for pid in range(2)]
    procs = [
        _spawn(pid, port, [str(n), "torus3d", "gossip"], outs[pid])
        for pid in range(2)
    ]
    logs = []
    for pr in procs:
        out_bytes, _ = pr.communicate(timeout=300)
        logs.append(out_bytes.decode(errors="replace"))
    _skip_if_unsupported(logs)
    assert all(pr.returncode == 0 for pr in procs), logs

    rec0 = json.loads(outs[0].read_text().splitlines()[-1])
    assert rec0["rounds"] == ref.rounds
    assert rec0["converged_count"] == ref.converged_count
    assert rec0["converged"] is True
    # Non-lead process runs every collective but stays silent on stdout.
    assert "Convergence Time" in logs[0]
    assert "Convergence Time" not in logs[1]


def _run_pair(tmp_path, port, cli_args, expect_rc=(0,), timeout=300):
    outs = [tmp_path / f"rec{pid}.jsonl" for pid in range(2)]
    procs = [_spawn(pid, port, cli_args, outs[pid]) for pid in range(2)]
    logs = []
    for pr in procs:
        out_bytes, _ = pr.communicate(timeout=timeout)
        logs.append(out_bytes.decode(errors="replace"))
    _skip_if_unsupported(logs)
    assert all(pr.returncode in expect_rc for pr in procs), logs
    return json.loads(outs[0].read_text().splitlines()[-1])


def test_two_process_pool_gossip_exact(tmp_path):
    # The other delivery family across processes: implicit-full offset-pool
    # sampling (packed choice words sliced per shard) with scatter +
    # psum_scatter delivery. Gossip state is integer, so the two-process run
    # must reproduce the single-process mesh bit-for-bit — this pins the
    # random stream (pool offsets + packed choices) as process-count-
    # invariant.
    n = 1024
    ref = run(
        build_topology("full", n),
        SimConfig(n=n, topology="full", algorithm="gossip",
                  delivery="pool", n_devices=8),
    )
    assert ref.converged
    rec0 = _run_pair(
        tmp_path, 21000 + (os.getpid() + 77) % 9000,
        [str(n), "full", "gossip", "--delivery", "pool"],
    )
    assert rec0["rounds"] == ref.rounds
    assert rec0["converged_count"] == ref.converged_count


def test_two_process_checkpoint_resume(tmp_path):
    # Multi-process checkpointing: state spans processes, so the CLI gathers
    # it (process_allgather — a collective all processes join) and only the
    # lead writes; resume re-shards it through the callback-based dev_put.
    # Gossip integer state + process-invariant stream => the resumed pair
    # must land on the uninterrupted pair's exact round count.
    n = 4096
    full = _run_pair(
        tmp_path, 21000 + (os.getpid() + 231) % 9000,
        [str(n), "torus3d", "gossip"],
    )
    assert full["converged"] is True

    ck = tmp_path / "state.npz"
    halted = _run_pair(
        tmp_path, 21000 + (os.getpid() + 308) % 9000,
        [str(n), "torus3d", "gossip", "--max-rounds", "24",
         "--chunk-rounds", "8", "--checkpoint", str(ck)],
        expect_rc={1},  # capped before convergence
    )
    assert halted["converged"] is False
    assert ck.exists()

    resumed = _run_pair(
        tmp_path, 21000 + (os.getpid() + 385) % 9000,
        [str(n), "torus3d", "gossip", "--chunk-rounds", "8",
         "--resume", str(ck)],
    )
    assert resumed["rounds"] == full["rounds"]
    assert resumed["converged_count"] == full["converged_count"]


def test_two_process_fused_sharded_lattice(tmp_path):
    # VERDICT r3 #8: the fused x sharded composition under REAL two-OS-
    # process collectives. At chunk_rounds=1 the per-shard Pallas chunks
    # (interpret mode on CPU) + halo ppermutes must reproduce the
    # single-process 8-virtual-device run exactly — gossip state is
    # integer, so rounds and counts match bit-for-bit. Population: the
    # smallest torus whose layout splits into whole 512-row tiles on 8
    # devices (128^3 -> 16384 rows) — large for interpret mode, but the
    # run is capped at 8 rounds (measured: both fused two-process tests
    # together finish in ~60 s).
    n = 128**3
    args = [str(n), "torus3d", "gossip", "--engine", "fused",
            "--chunk-rounds", "1", "--max-rounds", "8"]
    ref = run(
        build_topology("torus3d", n),
        SimConfig(n=n, topology="torus3d", algorithm="gossip",
                  engine="fused", chunk_rounds=1, max_rounds=8,
                  n_devices=8),
    )
    rec0 = _run_pair(
        tmp_path, 21000 + (os.getpid() + 462) % 9000, args,
        expect_rc={0, 1},  # capped before convergence
        timeout=600,
    )
    assert rec0["rounds"] == ref.rounds
    assert rec0["converged_count"] == ref.converged_count


def test_two_process_fused_pool_sharded(tmp_path):
    # The implicit-full pool composition across processes: one all_gather
    # of the send planes per round now crosses the process boundary.
    # Gossip ints: the two-process run must match the single-process mesh
    # (itself bitwise the single-device fused pool engine) exactly.
    n = 2**20
    args = [str(n), "full", "gossip", "--delivery", "pool",
            "--engine", "fused", "--max-rounds", "12"]
    ref = run(
        build_topology("full", n),
        SimConfig(n=n, topology="full", algorithm="gossip",
                  delivery="pool", engine="fused", max_rounds=12,
                  n_devices=8),
    )
    rec0 = _run_pair(
        tmp_path, 21000 + (os.getpid() + 539) % 9000, args,
        expect_rc={0, 1},
        timeout=600,
    )
    assert rec0["rounds"] == ref.rounds
    assert rec0["converged_count"] == ref.converged_count


def test_two_process_pool_pushsum(tmp_path):
    # Push-sum across processes: gloo's cross-process reductions may
    # reassociate float sums differently from the single-process mesh, and
    # the 3-consecutive-stable-rounds termination test amplifies any ulp
    # difference into a different round count — so the oracle here is
    # convergence quality, not the exact trajectory (the integer gossip
    # tests above pin stream identity). Also exercises the jnp-based
    # estimate-MAE reductions over process-spanning (non-host-addressable)
    # state arrays.
    n = 1024
    ref = run(
        build_topology("full", n),
        SimConfig(n=n, topology="full", algorithm="push-sum",
                  delivery="pool", n_devices=8),
    )
    assert ref.converged
    rec0 = _run_pair(
        tmp_path, 21000 + (os.getpid() + 154) % 9000,
        [str(n), "full", "push-sum", "--delivery", "pool"],
    )
    assert rec0["converged"] is True
    assert rec0["converged_count"] == n
    assert rec0["estimate_mae"] < 1e-2
    assert rec0["rounds"] < 3 * ref.rounds
