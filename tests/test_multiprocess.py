"""True multi-process distributed runs (jax.distributed over two OS
processes, gloo CPU collectives) — the multi-host story executed for real,
not just on a single-process virtual mesh.

The reference's only nod at distribution is an unused Akka.Cluster package
reference (project3.fsproj:13-15, never configured — SURVEY.md C14). Here
two processes each host half the global device mesh and run the SAME
shard_map collective program via the public CLI (`--coordinator
--num-processes --process-id`); the per-round halo ppermutes / banded
reduce_scatters / summary gathers and the psum convergence predicate all
cross the process boundary. The oracle is the single-process
8-virtual-device run: gossip state is integer, and the random stream is
device-count- and process-count-invariant by construction
(ops/sampling.py), so rounds and converged counts must match exactly.

Spawning/skip-gating/child-failure passthrough live in tests/_mp.py
(ISSUE 15 satellite) — the same harness scripts/multihost_smoke.py drives
in CI. ISSUE 15 extends the covered compositions to the ring compositions
that hold the ceilings: the HBM-streaming sharded composition
(fused_hbm_sharded — under a multi-process mesh the VMEM composition's
plan refuses and the dispatch routes here at any population) and
replicated-pool2 (pool2_sharded, both delivery wires).
"""

from __future__ import annotations

import pytest

from tests._mp import spawn_pair

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run

# Two-OS-process jax.distributed runs: minutes of subprocess spawns on a
# capable runtime, and pure spawn overhead where the CPU backend lacks
# multiprocess collectives — outside the tier-1 budget either way.
pytestmark = pytest.mark.slow


def test_two_process_sharded_matches_single_process(tmp_path):
    n = 4096  # 16^3 torus: halo-exchange delivery, ppermutes cross processes
    ref = run(
        build_topology("torus3d", n),
        SimConfig(n=n, topology="torus3d", algorithm="gossip", n_devices=8),
    )
    assert ref.converged

    rec0, logs = spawn_pair(tmp_path, [str(n), "torus3d", "gossip"])
    assert rec0["rounds"] == ref.rounds
    assert rec0["converged_count"] == ref.converged_count
    assert rec0["converged"] is True
    # Non-lead process runs every collective but stays silent on stdout.
    assert "Convergence Time" in logs[0]
    assert "Convergence Time" not in logs[1]


def test_two_process_pool_gossip_exact(tmp_path):
    # The other delivery family across processes: implicit-full offset-pool
    # sampling (packed choice words sliced per shard) with scatter +
    # psum_scatter delivery. Gossip state is integer, so the two-process run
    # must reproduce the single-process mesh bit-for-bit — this pins the
    # random stream (pool offsets + packed choices) as process-count-
    # invariant.
    n = 1024
    ref = run(
        build_topology("full", n),
        SimConfig(n=n, topology="full", algorithm="gossip",
                  delivery="pool", n_devices=8),
    )
    assert ref.converged
    rec0, _ = spawn_pair(
        tmp_path, [str(n), "full", "gossip", "--delivery", "pool"]
    )
    assert rec0["rounds"] == ref.rounds
    assert rec0["converged_count"] == ref.converged_count


def test_two_process_checkpoint_resume(tmp_path):
    # Multi-process checkpointing: state spans processes, so the CLI gathers
    # it (process_allgather — a collective all processes join) and only the
    # lead writes; resume re-shards it through the callback-based dev_put.
    # Gossip integer state + process-invariant stream => the resumed pair
    # must land on the uninterrupted pair's exact round count.
    n = 4096
    full, _ = spawn_pair(tmp_path, [str(n), "torus3d", "gossip"])
    assert full["converged"] is True

    ck = tmp_path / "state.npz"
    halted, _ = spawn_pair(
        tmp_path,
        [str(n), "torus3d", "gossip", "--max-rounds", "24",
         "--chunk-rounds", "8", "--checkpoint", str(ck)],
        expect_rc={1},  # capped before convergence
    )
    assert halted["converged"] is False
    assert ck.exists()

    resumed, _ = spawn_pair(
        tmp_path,
        [str(n), "torus3d", "gossip", "--chunk-rounds", "8",
         "--resume", str(ck)],
    )
    assert resumed["rounds"] == full["rounds"]
    assert resumed["converged_count"] == full["converged_count"]


def test_two_process_fused_sharded_lattice(tmp_path):
    # VERDICT r3 #8, re-homed by ISSUE 15: under a multi-process mesh the
    # VMEM fused x sharded plan REFUSES (single-process device_put) and
    # the dispatch routes to the HBM-streaming sharded composition — so
    # this drives fused_hbm_sharded's cross-process wires (batched halo
    # ppermute pair + deferred verdict psum) at a population the VMEM
    # composition would otherwise own. Bitwise the single-process
    # composition it lands on. 128^3 -> 16384 rows over 8 devices; capped
    # at 8 rounds (interpret mode).
    n = 128**3
    args = [str(n), "torus3d", "gossip", "--engine", "fused",
            "--chunk-rounds", "1", "--max-rounds", "8"]
    # Spawn first: the no-gloo skip gate fires before the (expensive)
    # interpret-mode single-process oracle is computed.
    rec0, _ = spawn_pair(
        tmp_path, args,
        expect_rc={0, 1},  # capped before convergence
        timeout=600,
    )
    from cop5615_gossip_protocol_tpu.parallel.fused_hbm_sharded import (
        run_stencil_hbm_sharded,
    )
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh

    ref = run_stencil_hbm_sharded(
        build_topology("torus3d", n),
        SimConfig(n=n, topology="torus3d", algorithm="gossip",
                  engine="fused", chunk_rounds=1, max_rounds=8,
                  n_devices=8),
        mesh=make_mesh(8),
    )
    assert rec0["rounds"] == ref.rounds
    assert rec0["converged_count"] == ref.converged_count


def test_two_process_fused_hbm_sharded_ring(tmp_path):
    # ISSUE 15 acceptance: the HBM-streaming sharded composition under
    # the two-OS-process gloo mesh, bitwise the single-process virtual
    # mesh (which the slow suite pins bitwise the chunked engine). The
    # ring wire: ONE batched halo ppermute pair + the deferred verdict
    # psum per super-step, now crossing the process boundary. 2^20 nodes
    # -> 8192 rows -> 1024-row shards (the hbm plan needs whole
    # processing tiles per shard; 65536 would leave 64-row shards).
    n = 1 << 20
    args = [str(n), "ring", "gossip", "--engine", "fused",
            "--chunk-rounds", "2", "--max-rounds", "8"]
    rec0, _ = spawn_pair(tmp_path, args, expect_rc={0, 1}, timeout=600)
    from cop5615_gossip_protocol_tpu.parallel.fused_hbm_sharded import (
        run_stencil_hbm_sharded,
    )
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh

    ref = run_stencil_hbm_sharded(
        build_topology("ring", n),
        SimConfig(n=n, topology="ring", algorithm="gossip",
                  engine="fused", chunk_rounds=2, max_rounds=8,
                  n_devices=8),
        mesh=make_mesh(8),
    )
    assert rec0["rounds"] == ref.rounds
    assert rec0["converged_count"] == ref.converged_count


@pytest.mark.parametrize("wire", ["reduce_scatter", "all_gather"])
def test_two_process_pool2_sharded_exact(tmp_path, wire):
    # ISSUE 15 acceptance: replicated-pool2 under the two-OS-process gloo
    # mesh, BOTH delivery wires, bitwise the single-process virtual mesh.
    # delivery='matmul' routes the implicit-full fused dispatch straight
    # to the pool2 composition at any population; gossip ints pin the
    # banded reduce_scatter / summary all_gather + verdict psum across
    # the process boundary exactly.
    n = 262_144
    args = [str(n), "full", "gossip", "--delivery", "matmul",
            "--engine", "fused", "--max-rounds", "8",
            "--chunk-rounds", "1", "--pool2-wire", wire]
    rec0, _ = spawn_pair(tmp_path, args, expect_rc={0, 1}, timeout=600)
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh
    from cop5615_gossip_protocol_tpu.parallel.pool2_sharded import (
        run_pool2_sharded,
    )

    ref = run_pool2_sharded(
        build_topology("full", n),
        SimConfig(n=n, topology="full", algorithm="gossip",
                  delivery="matmul", engine="fused", chunk_rounds=1,
                  max_rounds=8, n_devices=8, pool2_wire=wire),
        mesh=make_mesh(8),
    )
    assert rec0["rounds"] == ref.rounds
    assert rec0["converged_count"] == ref.converged_count


def test_two_process_pool_pushsum(tmp_path):
    # Push-sum across processes: gloo's cross-process reductions may
    # reassociate float sums differently from the single-process mesh, and
    # the 3-consecutive-stable-rounds termination test amplifies any ulp
    # difference into a different round count — so the oracle here is
    # convergence quality, not the exact trajectory (the integer gossip
    # tests above pin stream identity). Also exercises the jnp-based
    # estimate-MAE reductions over process-spanning (non-host-addressable)
    # state arrays.
    n = 1024
    ref = run(
        build_topology("full", n),
        SimConfig(n=n, topology="full", algorithm="push-sum",
                  delivery="pool", n_devices=8),
    )
    assert ref.converged
    rec0, _ = spawn_pair(
        tmp_path, [str(n), "full", "push-sum", "--delivery", "pool"]
    )
    assert rec0["converged"] is True
    assert rec0["converged_count"] == n
    assert rec0["estimate_mae"] < 1e-2
    assert rec0["rounds"] < 3 * ref.rounds
