"""Reference-fidelity mode: single-walk push-sum (SURVEY.md §3.3) and the
observable quirks Q1-Q9 at the run() level."""

import jax
import jax.numpy as jnp
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
from cop5615_gossip_protocol_tpu.models import reference as R
from cop5615_gossip_protocol_tpu.models.runner import draw_leader


def _cfg(n, kind, **kw):
    return SimConfig(
        n=n, topology=kind, algorithm="push-sum", semantics="reference",
        dtype="float64", **kw,
    )


def test_walk_mass_conservation():
    # Total mass (arrays + in-flight message) is invariant hop by hop.
    cfg = _cfg(20, "full")
    topo = build_topology("full", 20, semantics="reference")
    key = jax.random.PRNGKey(0)
    leader = draw_leader(key, topo, cfg)
    step_fn, carry, kd, targs = R.make_walk(topo, cfg, key, leader)
    total0 = float(jnp.sum(carry.s) + carry.msg_s)
    w_total0 = float(jnp.sum(carry.w) + carry.msg_w)
    assert total0 == pytest.approx(topo.n * (topo.n - 1) / 2)
    assert w_total0 == pytest.approx(topo.n)
    for _ in range(200):
        carry = step_fn(carry, kd, *targs)
        assert float(jnp.sum(carry.s) + carry.msg_s) == pytest.approx(total0, rel=1e-12)
        assert float(jnp.sum(carry.w) + carry.msg_w) == pytest.approx(w_total0, rel=1e-12)


def test_walk_one_message_in_flight():
    # Each hop touches exactly one node's state (or none, for a converged
    # relay) — the defining property of the reference's push-sum.
    cfg = _cfg(20, "full")
    topo = build_topology("full", 20, semantics="reference")
    key = jax.random.PRNGKey(1)
    leader = draw_leader(key, topo, cfg)
    step_fn, carry, kd, targs = R.make_walk(topo, cfg, key, leader)
    for _ in range(100):
        nxt = step_fn(carry, kd, *targs)
        changed = int(jnp.sum((nxt.s != carry.s) | (nxt.w != carry.w)))
        assert changed <= 1
        assert int(nxt.steps) == int(carry.steps) + 1
        carry = nxt


def test_walk_converges_full_small():
    # `dotnet run 20 full push-sum` converges in the reference (28.9 ms,
    # report.pdf p.3); the walk must converge here too.
    cfg = _cfg(20, "full", max_rounds=500_000)
    topo = build_topology("full", 20, semantics="reference")
    r = run(topo, cfg)
    assert r.converged
    assert r.target_count == 20 and r.population == 21  # Q1
    # Walk-mode estimates are stale (Q5 pre-absorb reporting) but bounded.
    assert r.estimate_mae < topo.n


def test_walk_converged_relay_freezes_state():
    # Q5: a converged node's receipt relays the message untouched.
    cfg = _cfg(10, "full")
    topo = build_topology("full", 10, semantics="reference")
    key = jax.random.PRNGKey(2)
    leader = draw_leader(key, topo, cfg)
    step_fn, carry, kd, targs = R.make_walk(topo, cfg, key, leader)
    carry = carry._replace(conv=carry.conv.at[int(carry.cur)].set(True))
    nxt = step_fn(carry, kd, *targs)
    cur = int(carry.cur)
    assert float(nxt.s[cur]) == float(carry.s[cur])
    assert float(nxt.msg_s) == float(carry.msg_s)  # relayed unchanged
    assert float(nxt.msg_w) == float(carry.msg_w)


def test_walk_dies_on_orphan_q8():
    # An orphan (degree 0) kills the walk — the reference actor crashes on
    # the empty neighbor array and the message is lost in the restart.
    import numpy as np

    from cop5615_gossip_protocol_tpu.ops.topology import Topology

    neighbors = np.array([[1], [0], [0]], dtype=np.int32)
    degree = np.array([1, 1, 0], dtype=np.int32)  # node 2 is an orphan
    topo = Topology("line", 3, 3, 3, 1, neighbors, degree)
    cfg = _cfg(3, "line")
    key = jax.random.PRNGKey(0)
    step_fn, carry, kd, targs = R.make_walk(topo, cfg, key, jnp.int32(0))
    carry = carry._replace(cur=jnp.int32(2))  # force the walk onto the orphan
    nxt = step_fn(carry, kd, *targs)
    assert bool(nxt.dead)


def test_reference_run_dispatches_to_walk():
    # rounds == message hops in walk mode: far more hops than the ~dozens of
    # synchronous rounds batched mode needs at this size.
    topo = build_topology("full", 32, semantics="reference")
    r = run(topo, _cfg(32, "full", max_rounds=500_000))
    assert r.semantics == "reference"
    assert r.rounds > 100


def test_batched_vs_reference_agree_on_the_answer():
    # Same protocol, two execution models — both must estimate the mean.
    kind = "full"
    t_ref = build_topology(kind, 64, semantics="reference")
    r_ref = run(t_ref, _cfg(64, kind, max_rounds=1_000_000))
    t_hon = build_topology(kind, 64)
    r_hon = run(t_hon, SimConfig(n=64, topology=kind, algorithm="push-sum", dtype="float64"))
    assert r_ref.converged and r_hon.converged
    assert r_hon.estimate_mae < 1e-6
    assert r_ref.estimate_mae < 5.0  # walk-mode staleness (Q5), bounded
