"""AST-scanned lint fixture: host conversions inside a traced scope.

Never imported — jax/np here are names for the AST walker, not runtime
dependencies. Each marked line must produce one lint/traced-* finding.
"""

import numpy as np

from jax import lax


def runner(n, plane):
    def cond(carry):
        return carry < n

    def body(carry):
        host = int(carry)           # lint: traced-int (param to host)
        arr = np.asarray(carry)     # lint: traced-np-asarray
        scalar = arr.item()         # lint: traced-item
        return carry + host + scalar

    return lax.while_loop(cond, body, plane)
