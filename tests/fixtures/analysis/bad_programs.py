"""Seeded-bad jax programs — each trips exactly one jaxpr-level checker.

Imported by tests/test_static_audit.py; every builder returns ``(fn,
args)`` ready for analysis.trace.TracedCell, plus the deliberately-wrong
wire declaration for the wire-spec pin. See README.md in this directory.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from cop5615_gossip_protocol_tpu.analysis.wire_specs import (
    C,
    Regions,
    WireSpec,
)


def host_sync_chunk():
    """A debug print (-> debug_callback) inside the while body: one
    device->host round-trip per round."""

    def fn(x):
        def body(c):
            jax.debug.print("round {c}", c=c)
            return c + 1

        return lax.while_loop(lambda c: c < 8, body, x)

    return fn, (jnp.int32(0),)


def clean_chunk():
    """The same loop without the callback — the negative pin."""

    def fn(x):
        return lax.while_loop(lambda c: c < 8, lambda c: c + 1, x)

    return fn, (jnp.int32(0),)


def f64_promotion_chunk():
    """A strongly-typed np.float64 scalar reaching f32 arithmetic in the
    body: under an x64 trace the carry promotes to float64 — the classic
    'fine on CPU-without-x64, doubles HBM traffic under x64' bug."""

    def fn(x):
        def body(c):
            return (c * np.float64(0.5)).astype(jnp.float32) + c

        return lax.while_loop(lambda c: jnp.all(c < 8.0), body, x)

    return fn, (jnp.zeros((4,), jnp.float32),)


def clean_f32_chunk():
    """Same body with the scalar pinned to f32 — the negative pin."""

    def fn(x):
        def body(c):
            return c * jnp.float32(0.5) + c

        return lax.while_loop(lambda c: jnp.all(c < 8.0), body, x)

    return fn, (jnp.zeros((4,), jnp.float32),)


def unaliased_donated_chunk():
    """Jitted WITHOUT donate_argnums while the run reports donate=True:
    the state carry has no aliasing attribute in the lowering — the
    donated buffer would be silently copied every chunk."""
    fn = jax.jit(lambda s, r: (s + 1.0, r + 1))
    return fn, (jnp.zeros((8,), jnp.float32), jnp.int32(0))


def donated_chunk():
    """Properly donated carry — the negative pin (aliases through to the
    compiled input_output_alias map)."""
    fn = jax.jit(lambda s, r: (s + 1.0, r + 1), donate_argnums=(0,))
    return fn, (jnp.zeros((8,), jnp.float32), jnp.int32(0))


def scatter_delivery_chunk():
    """A 'matmul-tier' chunk whose round body delivers by scatter-add and
    never touches the MXU: the matmul-delivery checker must flag BOTH the
    missing dot_general and the scatter (the silent fallback onto the
    dynamic-address path)."""

    def fn(state, targets):
        def body(c):
            vals, r = c
            inbox = jnp.zeros_like(vals).at[targets].add(vals)
            return (inbox, r + 1)

        return lax.while_loop(lambda c: c[1] < 8, body, (state, 0))

    return fn, (jnp.ones((32,), jnp.float32),
                jnp.arange(32, dtype=jnp.int32)[::-1])


def matmul_delivery_chunk():
    """The negative pin: the same delivery as a one-hot dot_general —
    exactly one MXU contraction, zero scatters."""

    def fn(state, targets):
        onehot = (
            targets[:, None] == jnp.arange(32, dtype=jnp.int32)[None, :]
        ).astype(jnp.float32)

        def body(c):
            vals, r = c
            inbox = lax.dot_general(
                vals, onehot, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return (inbox, r + 1)

        return lax.while_loop(lambda c: c[1] < 8, body, (state, 0))

    return fn, (jnp.ones((32,), jnp.float32),
                jnp.arange(32, dtype=jnp.int32)[::-1])


def host_callback_refill():
    """A lane-refill program that consults the host per refill (ISSUE 14):
    the refill-path lint (contracts.check_host_sync_whole) must flag the
    callback — the refill decision's contract is host-side/clock-only,
    pure selects over the batch carry."""

    def fn(state, mask, fresh):
        jax.debug.callback(lambda m: None, mask)
        return jnp.where(mask[:, None], fresh, state)

    return fn, (
        jnp.zeros((4, 8), jnp.float32),
        jnp.zeros((4,), bool),
        jnp.ones((4, 8), jnp.float32),
    )


def clean_refill():
    """The same refill as pure selects — the negative pin."""

    def fn(state, mask, fresh):
        return jnp.where(mask[:, None], fresh, state)

    return fn, (
        jnp.zeros((4, 8), jnp.float32),
        jnp.zeros((4,), bool),
        jnp.ones((4, 8), jnp.float32),
    )


def double_psum_chunk(mesh, axis):
    """TWO verdict psums per round where the declaration below says ONE —
    the wire-spec diff must flag body-psum (and nothing else)."""
    from cop5615_gossip_protocol_tpu.utils import compat
    from jax.sharding import PartitionSpec as P

    def chunk(x):
        def body(c):
            once = lax.psum(c, axis)
            twice = lax.psum(once, axis)
            return twice

        return lax.while_loop(lambda c: jnp.all(c < 8.0), body, x)

    fn = jax.jit(compat.shard_map(
        chunk, mesh=mesh, in_specs=(P(axis),), out_specs=P(axis),
    ))
    return fn, (jnp.zeros((8,), jnp.float32),)


# The declaration the double-psum program violates: one verdict psum per
# round, nothing else on the wire.
FIXTURE_WIRE_SPEC = WireSpec(
    engine="fixture-engine",
    variants={
        ("overlap", "wire"): Regions(
            body={"psum": C(fixed=1)}, setup={},
        ),
    },
    mechanism={},
)
