"""AST-scanned lint fixture: schema-version discipline violations.

Never imported. The row builder writes a literal version (must source the
constant), and a second constant is defined but never read.
"""

ROW_SCHEMA_VERSION = 3
ORPHAN_SCHEMA_VERSION = 9
TYPED_SCHEMA_VERSION: int = 7  # annotated constants count too


def build_row(payload):
    return {
        "schema_version": 3,  # lint: schema-literal (constant bypassed)
        "payload": payload,
    }
