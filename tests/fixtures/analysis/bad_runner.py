"""AST-scanned lint fixture: a runner-ladder refusal that dead-ends.

Never imported. The refusal names an engine override but no real serving
composition or alternative route — the PR 10 rule the refusal lint
enforces.
"""


def _run_resolved(topo, cfg):
    if cfg.engine == "fused":
        raise ValueError(
            "engine='fused' is unavailable for this request"
            # lint: refusal-dead-end — no composition named
        )
    if cfg.engine == "other":
        # Interpolated DATA does not exempt the static text around it:
        # this must fire too (only a computed *_support reason delegates).
        raise ValueError(
            f"engine='other' is unsupported for topology {cfg.topology}"
            # lint: refusal-dead-end
        )
    if cfg.engine == "auto":
        reason = _support(topo)
        # Delegated to a computed reason — judged by that surface, not
        # here; must NOT fire.
        raise ValueError(f"engine='auto' unavailable: {reason}")
    return topo


def _support(topo):
    return f"population {topo.n} exceeds the budget"
