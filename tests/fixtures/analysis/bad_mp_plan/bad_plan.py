"""AST-scanned lint fixture: a multi-process plan refusal that dead-ends.

Never imported. The plan function refuses a multi-process mesh without
naming any serving composition — the ISSUE 15 support-matrix rule the
``check_multiprocess_refusals`` lint enforces; the second return names
the chunked sharded engine and must NOT fire.
"""


def plan_bad_composition(topo, cfg, n_dev):
    if cfg.processes > 1:
        return (
            "this thing is single-process only; nothing more to say"
            # lint: refusal-dead-end — no composition named
        )
    if cfg.processes > 2:
        return (
            "this plan is single-process; multi-process meshes serve "
            "the chunked sharded engine instead"  # must NOT fire
        )
    return (1, 2, 3)
