"""AST-scanned PRNG-tag fixture: three ways to break the TAG MAP.

Never imported. An unregistered ``*_TAG`` constant, a literal fold
outside every registered region, and a fold through the unregistered
constant — each must produce one prng-tags finding.
"""

from jax import random
from jax.random import fold_in

ROGUE_TAG = 12345  # prng-tags: unregistered-tag-constant
TYPED_TAG: int = 54321  # prng-tags: unregistered-tag-constant (annotated)


def draw(key):
    a = random.fold_in(key, 4294967295)  # prng-tags: literal-tag-outside-map
    b = random.fold_in(key, ROGUE_TAG)   # prng-tags: unregistered-tag-fold
    # Bare from-import call form — must be just as visible to the harvest.
    c = fold_in(key, 4294967294)         # prng-tags: literal-tag-outside-map
    d = fold_in(key, data=TYPED_TAG)     # prng-tags: unregistered-tag-fold
    return a, b, c, d
