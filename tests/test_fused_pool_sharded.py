"""Fused pool x sharded composition (parallel/fused_pool_sharded.py).

The implicit-full flagship across devices: local halve, one all_gather of
the send planes per round, single-device pool-kernel delivery+absorb per
shard. The design claim is BITWISE equality with the single-device fused
pool engine at every device count (same tile arithmetic on the same
operands) — which transitively matches the chunked collective pool path
(tests/test_halo.py pins that leg). Pinned here: gossip int state, push-sum
float state to the last bit, global termination, resume, plan gating.

Geometry note: the pool layout's 512-row tiles mean the smallest sharded
populations are 131072 (2 devices) / 262144 (4 devices); rounds are bounded
where convergence would cost interpret-mode minutes.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.parallel.fused_pool_sharded import (
    plan_fused_pool_sharded,
    run_fused_pool_sharded,
)
from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh


def _cfg(n, algorithm="gossip", **kw):
    kw.setdefault("delivery", "pool")
    kw.setdefault("engine", "fused")
    kw.setdefault("max_rounds", 200)
    return SimConfig(n=n, topology="full", algorithm=algorithm, **kw)


def test_gossip_bitwise_vs_single_device():
    n = 131072
    topo = build_topology("full", n)
    final = {}
    r1 = run(topo, _cfg(n), on_chunk=lambda r, s: final.__setitem__("a", s))
    r2 = run_fused_pool_sharded(
        topo, _cfg(n, n_devices=2), mesh=make_mesh(2),
        on_chunk=lambda r, s: final.__setitem__("b", s),
    )
    assert r1.converged and r2.converged
    assert r1.rounds == r2.rounds
    assert r1.converged_count == r2.converged_count
    a, b = final["a"], final["b"]
    assert (np.asarray(a.count) == np.asarray(b.count)).all()
    assert (np.asarray(a.active) == np.asarray(b.active)).all()


def test_gossip_bitwise_vs_chunked_collective():
    # VERDICT r3 #1's oracle: the chunked collective pool path
    # (parallel/halo.deliver_pool_sharded) on the same mesh.
    n = 131072
    topo = build_topology("full", n)
    r_f = run_fused_pool_sharded(topo, _cfg(n, n_devices=2), mesh=make_mesh(2))
    cfg_c = _cfg(n, n_devices=2, engine="chunked")
    r_c = run(topo, cfg_c)
    assert r_f.rounds == r_c.rounds
    assert r_f.converged_count == r_c.converged_count


def test_gossip_padded_population():
    # n_pad > n: the mod-n blend + valid masks must keep pad lanes inert.
    n = 250000  # rows -> 2048, n_pad = 262144
    topo = build_topology("full", n)
    final = {}
    r1 = run(topo, _cfg(n), on_chunk=lambda r, s: final.__setitem__("a", s))
    r2 = run_fused_pool_sharded(
        topo, _cfg(n, n_devices=4), mesh=make_mesh(4),
        on_chunk=lambda r, s: final.__setitem__("b", s),
    )
    assert r1.rounds == r2.rounds
    assert r1.converged_count == r2.converged_count == n
    assert (np.asarray(final["a"].count) == np.asarray(final["b"].count)).all()


def test_pushsum_state_bitwise():
    n = 131072
    topo = build_topology("full", n)
    final = {}
    kw = dict(max_rounds=60, chunk_rounds=60)
    run(topo, _cfg(n, "push-sum", **kw),
        on_chunk=lambda r, s: final.__setitem__("a", s))
    run_fused_pool_sharded(
        topo, _cfg(n, "push-sum", n_devices=2, **kw), mesh=make_mesh(2),
        on_chunk=lambda r, s: final.__setitem__("b", s),
    )
    a, b = final["a"], final["b"]
    # Same float ops in the same order on every tile: bitwise, not just close.
    assert (np.asarray(a.s) == np.asarray(b.s)).all()
    assert (np.asarray(a.w) == np.asarray(b.w)).all()
    assert (np.asarray(a.term) == np.asarray(b.term)).all()
    sm = float(np.asarray(b.s, np.float64).sum())
    true = n * (n - 1) / 2
    assert abs(sm - true) / true < 1e-6  # mass conserved


def test_pushsum_global_termination():
    n = 131072
    topo = build_topology("full", n)
    kw = dict(termination="global", max_rounds=5000)
    r1 = run(topo, _cfg(n, "push-sum", **kw))
    r2 = run_fused_pool_sharded(
        topo, _cfg(n, "push-sum", n_devices=2, **kw), mesh=make_mesh(2)
    )
    assert r1.converged and r2.converged
    assert r1.rounds == r2.rounds
    assert r2.converged_count == n


def test_resume_midway():
    n = 131072
    topo = build_topology("full", n)
    cfg = _cfg(n, "push-sum", n_devices=2, max_rounds=60, chunk_rounds=20)
    snaps = []
    mesh = make_mesh(2)
    run_fused_pool_sharded(
        topo, cfg, mesh=mesh, on_chunk=lambda r, s: snaps.append((r, s))
    )
    assert len(snaps) >= 2
    r0, s0 = snaps[0]
    final = snaps[-1][1]
    resumed = {}
    run_fused_pool_sharded(
        topo, cfg, mesh=mesh,
        start_state=jax.tree.map(jnp.asarray, s0), start_round=r0,
        on_chunk=lambda r, s: resumed.__setitem__("s", s),
    )
    assert (np.asarray(resumed["s"].s) == np.asarray(final.s)).all()
    assert (np.asarray(resumed["s"].w) == np.asarray(final.w)).all()


def test_runner_dispatch_routes_pool_composition(monkeypatch):
    from cop5615_gossip_protocol_tpu.parallel import fused_pool_sharded as fps

    called = {}
    orig = fps.run_fused_pool_sharded

    def spy(*a, **kw):
        called["yes"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(fps, "run_fused_pool_sharded", spy)
    n = 131072
    r = run(build_topology("full", n),
            _cfg(n, n_devices=2, max_rounds=60))
    assert called.get("yes")
    assert r.rounds > 0


def test_plan_gating():
    cfg = _cfg(131072, n_devices=2)
    assert not isinstance(
        plan_fused_pool_sharded(build_topology("full", 131072), cfg, 2), str
    )
    assert "implicit full" in plan_fused_pool_sharded(
        build_topology("torus3d", 4096), cfg, 2
    )
    assert "delivery='pool'" in plan_fused_pool_sharded(
        build_topology("full", 131072), _cfg(131072, delivery="auto"), 2
    )
    assert "divide" in plan_fused_pool_sharded(
        build_topology("full", 131072), cfg, 3
    )
    big = 1 << 22
    assert "budget" in plan_fused_pool_sharded(
        build_topology("full", big), _cfg(big), 2
    )
