"""Byzantine adversary plane (ISSUE 16 tentpole): seeded adversaries,
robust push-sum aggregation, and the detection/mitigation pair.

Pinned contracts:

- the adversary plane is config-pure and seeded (ops/faults.byzantine_plane
  off BYZ_TAG — disjointness is machine-verified in analysis/tags.py and
  swept in tests/test_recovery.py); schedule counts are exact;
- mode x algorithm validity is config-enforced: push-sum adversaries
  corrupt the sent (s, w) wire pair, gossip adversaries corrupt protocol
  state — the cross pairings are hard errors;
- the acceptance pair: unmitigated mass_inflate trips the mass sentinel to
  outcome="unhealthy" at the EXACT onset round, and the same attack under
  --robust-agg clip converges with a bounded estimate MAE;
- gossip stale_rumor adversaries never converge (they reset to susceptible
  every round); garble adversaries fake convergence and poison the
  predicate;
- cross-engine parity: gossip trajectories under attack are bitwise
  chunked <-> fused (stencil and pool carriers); push-sum mass accounting
  agrees to float32 ulp scale;
- every composition that does not carry the plane refuses loudly, naming
  the serving composition (PR 10 rule, lint-enforced), and engine='auto'
  demotes to chunked instead;
- serving/keys.py folds the byzantine class (and robust_agg) into the
  bucket key; telemetry schema v3 reports byzantine_count; the trajectory
  analyzer marks adversarial onsets;
- the "round:count" schedule grammar is ONE helper shared by the crash,
  revive, and byzantine schedules with the error wording pinned once.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import faults, telemetry as telemetry_mod


def _run2(cfg):
    """(RunResult, final device state) via the chunk hook."""
    topo = build_topology(cfg.topology, cfg.n, seed=cfg.seed)
    final = {}
    r = run(topo, cfg, on_chunk=lambda rd, s: final.update(state=s))
    return r, final.get("state")


def _state_eq(sa, sb, float_atol=0.0):
    for x, y in zip(jax.tree.leaves(sa), jax.tree.leaves(sb)):
        x, y = np.asarray(x), np.asarray(y)
        if x.dtype.kind == "f" and float_atol:
            np.testing.assert_allclose(x, y, atol=float_atol, rtol=0)
        else:
            np.testing.assert_array_equal(x, y)


# ----------------------------------------------------------------- config


def test_mode_algorithm_validity_is_config_enforced():
    # Push-sum adversaries corrupt the wire pair; gossip adversaries
    # corrupt protocol state. The cross pairings are hard errors.
    for mode in ("mass_inflate", "mass_deflate", "garble"):
        SimConfig(n=64, topology="full", algorithm="push-sum",
                  byzantine_rate=0.1, byzantine_mode=mode)
    for mode in ("stale_rumor", "garble"):
        SimConfig(n=64, topology="full", algorithm="gossip",
                  byzantine_rate=0.1, byzantine_mode=mode)
    with pytest.raises(ValueError, match="does not apply"):
        SimConfig(n=64, topology="full", algorithm="gossip",
                  byzantine_rate=0.1, byzantine_mode="mass_inflate")
    with pytest.raises(ValueError, match="does not apply"):
        SimConfig(n=64, topology="full", algorithm="push-sum",
                  byzantine_rate=0.1, byzantine_mode="stale_rumor")


def test_rate_and_schedule_mutually_exclusive():
    with pytest.raises(ValueError, match="mutually exclusive"):
        SimConfig(n=64, topology="full", algorithm="push-sum",
                  byzantine_rate=0.1, byzantine_schedule="4:3")


def test_robust_agg_restrictions():
    # trim needs the full topology's uniform pool-slot channels.
    with pytest.raises(ValueError, match="trim"):
        SimConfig(n=64, topology="ring", algorithm="push-sum",
                  byzantine_rate=0.1, robust_agg="trim")
    # robust aggregation discards weight by design, so the conservation
    # sentinel is config-excluded.
    with pytest.raises(ValueError, match="robust_agg"):
        SimConfig(n=64, topology="full", algorithm="push-sum",
                  byzantine_rate=0.1, robust_agg="clip",
                  mass_tolerance=1e-3)


def test_robust_agg_without_byzantine_lints():
    with pytest.warns(RuntimeWarning, match="robust_agg without"):
        cfg = SimConfig(n=64, topology="full", algorithm="push-sum",
                        robust_agg="clip")
    assert any("robust_agg" in w for w in cfg.lint_warnings)
    # --byzantine-* without a crash model is fine: adversaries are ALIVE
    # (they send every round and count toward quorum), no lint fires.
    cfg = SimConfig(n=64, topology="full", algorithm="push-sum",
                    byzantine_rate=0.1)
    assert not any("byzantine" in w for w in cfg.lint_warnings)


def test_schedule_grammar_shared_wording():
    # Satellite: ONE parse helper for crash/revive/byzantine, the error
    # wording pinned here through every caller — only the kind differs.
    cases = [
        (dict(crash_schedule="4;3"), "crash"),
        (dict(crash_rate=0.01, revive_schedule="4;3"), "revive"),
        (dict(byzantine_schedule="4;3", byzantine_mode="garble"),
         "byzantine"),
    ]
    for kw, kind in cases:
        with pytest.raises(
            ValueError,
            match=f"{kind} schedule entry '4;3' is not 'round:count'",
        ):
            SimConfig(n=64, topology="full", **kw)
    with pytest.raises(ValueError, match="byzantine schedule count"):
        faults.parse_schedule("4:0", kind="byzantine")


# ------------------------------------------------------------------ plane


def test_byzantine_plane_schedule_counts_and_at():
    cfg = SimConfig(n=200, topology="full", algorithm="push-sum",
                    byzantine_schedule="3:10,7:5", seed=5)
    byz = faults.byzantine_plane(cfg, 200)
    assert int((byz == 3).sum()) == 10
    assert int((byz == 7).sum()) == 5
    assert int((byz == faults.NEVER).sum()) == 185
    at = np.asarray(faults.byzantine_at(jnp.asarray(byz), 6))
    assert int(at.sum()) == 10
    at = np.asarray(faults.byzantine_at(jnp.asarray(byz), 7))
    assert int(at.sum()) == 15
    # Pads are honest forever.
    padded = faults.pad_byzantine_plane(byz, 256)
    assert (padded[200:] == faults.NEVER).all()


# ------------------------------------------- the acceptance pair (push-sum)


def test_mass_inflate_unhealthy_at_exact_round_then_clip_converges():
    # Unmitigated mass_inflate must trip the conservation sentinel at the
    # EXACT round the adversaries turn; the same attack under clip
    # converges with a pinned estimate-MAE bound.
    base = dict(n=256, topology="full", algorithm="push-sum", seed=0,
                delivery="pool", chunk_rounds=32, max_rounds=2000,
                byzantine_schedule="12:8", byzantine_mode="mass_inflate")
    r = run(build_topology("full", 256),
            SimConfig(**base, mass_tolerance=1e-3))
    assert r.outcome == "unhealthy"
    assert r.unhealthy_round == 12
    assert not r.converged

    r2 = run(build_topology("full", 256),
             SimConfig(**base, robust_agg="clip"))
    assert r2.outcome == "converged"
    # n=256 values 0..255: true mean 127.5. Unmitigated estimates diverge
    # without bound; clipped ones stay within a few units.
    assert r2.estimate_mae < 5.0


def test_trim_bounds_the_same_attack_on_full_pool():
    base = dict(n=256, topology="full", algorithm="push-sum", seed=1,
                delivery="pool", chunk_rounds=32, max_rounds=2000,
                byzantine_rate=0.05, byzantine_mode="mass_inflate")
    r = run(build_topology("full", 256), SimConfig(**base, robust_agg="trim"))
    assert r.outcome == "converged"
    assert r.estimate_mae < 10.0


# ----------------------------------------------------------- gossip modes


def test_gossip_stale_rumor_adversaries_never_converge():
    cfg = SimConfig(n=128, topology="full", algorithm="gossip", seed=2,
                    byzantine_schedule="4:6", byzantine_mode="stale_rumor",
                    chunk_rounds=32, max_rounds=400)
    r, state = _run2(cfg)
    # 6 adversaries re-inject forever: the full-population target is
    # unreachable, and exactly the adversary set stays unconverged.
    assert r.outcome != "converged"
    byz = faults.byzantine_plane(cfg, 128)
    conv = np.asarray(state.conv).astype(bool)
    assert (~conv[byz != faults.NEVER]).all()
    assert conv[byz == faults.NEVER].all()


def test_gossip_garble_fakes_convergence():
    cfg = SimConfig(n=128, topology="full", algorithm="gossip", seed=2,
                    byzantine_schedule="4:6", byzantine_mode="garble",
                    chunk_rounds=32, max_rounds=400)
    honest = dataclasses_replace(cfg, byzantine_schedule=None)
    r, _ = _run2(cfg)
    rh, _ = _run2(honest)
    # Fake convergence reports can only pull the predicate EARLIER.
    assert r.outcome == "converged"
    assert r.rounds <= rh.rounds


def dataclasses_replace(cfg, **kw):
    import dataclasses

    return dataclasses.replace(cfg, **kw)


# ------------------------------------------------------ cross-engine parity


@pytest.mark.slow  # interpret-mode run pair; see tier-1 budget note in test_fused.py
@pytest.mark.parametrize("mode,topo_kind,extra", [
    ("stale_rumor", "ring", {}),
    ("garble", "full", {"delivery": "pool"}),
])
def test_gossip_byzantine_bitwise_chunked_vs_fused(mode, topo_kind, extra):
    cfg = SimConfig(n=256, topology=topo_kind, algorithm="gossip", seed=7,
                    byzantine_rate=0.05, byzantine_mode=mode,
                    chunk_rounds=32, max_rounds=300, **extra)
    ra, sa = _run2(dataclasses_replace(cfg, engine="chunked"))
    rb, sb = _run2(dataclasses_replace(cfg, engine="fused"))
    assert (ra.outcome, ra.rounds, ra.converged_count) == \
        (rb.outcome, rb.rounds, rb.converged_count)
    _state_eq(sa, sb)


@pytest.mark.slow  # interpret-mode run pair; see tier-1 budget note in test_fused.py
@pytest.mark.parametrize("mode", ["mass_inflate", "mass_deflate", "garble"])
def test_pushsum_byzantine_mass_parity_chunked_vs_fused_pool(mode):
    cfg = SimConfig(n=300, topology="full", algorithm="push-sum", seed=5,
                    delivery="pool", byzantine_rate=0.04,
                    byzantine_mode=mode, chunk_rounds=32, max_rounds=60)
    ra, sa = _run2(dataclasses_replace(cfg, engine="chunked"))
    rb, sb = _run2(dataclasses_replace(cfg, engine="fused"))
    assert ra.rounds == rb.rounds
    # Mass accounting across the corrupted-wire/honest-keep split: the
    # fused pool kernel inverts the corruption per tile (fp-exact ops), so
    # the engines' total mass agrees at float32 ulp scale.
    ma = float(np.asarray(sa.s, np.float64).sum())
    mb = float(np.asarray(sb.s, np.float64).sum())
    assert abs(ma - mb) <= 2 * np.spacing(np.float32(abs(ma) + 1.0)) * 300
    _state_eq(sa, sb, float_atol=1e-4)


@pytest.mark.slow  # interpret-mode run pair; see tier-1 budget note in test_fused.py
def test_pushsum_byzantine_stencil_parity_with_crash_revive():
    cfg = SimConfig(n=256, topology="ring", algorithm="push-sum", seed=3,
                    byzantine_rate=0.04, byzantine_mode="mass_inflate",
                    crash_rate=0.02, revive_rate=0.3,
                    chunk_rounds=32, max_rounds=60)
    ra, sa = _run2(dataclasses_replace(cfg, engine="chunked"))
    rb, sb = _run2(dataclasses_replace(cfg, engine="fused"))
    assert ra.rounds == rb.rounds
    _state_eq(sa, sb, float_atol=1e-4)


# -------------------------------------------------------------- refusals


def test_sharded_xla_refuses_byzantine_and_robust_agg():
    topo = build_topology("full", 128)
    cfg = SimConfig(n=128, topology="full", algorithm="push-sum",
                    byzantine_rate=0.1, n_devices=2, strict_engine=True)
    with pytest.raises(ValueError, match="sharded XLA composition"):
        run(topo, cfg)
    cfg = SimConfig(n=128, topology="full", algorithm="push-sum",
                    robust_agg="clip", byzantine_rate=0.1, n_devices=2,
                    strict_engine=True)
    with pytest.raises(ValueError, match="chunked"):
        run(topo, cfg)


def test_auto_engine_demotes_to_chunked_under_byzantine():
    # engine='auto' on a composition whose fused tier cannot carry the
    # plane or the countermeasure must demote, not crash: the chunked
    # round bodies own both.
    topo = build_topology("line", 256)
    cfg = SimConfig(n=256, topology="line", algorithm="push-sum",
                    delivery="scatter", byzantine_rate=0.05,
                    byzantine_mode="mass_inflate", robust_agg="clip",
                    chunk_rounds=32, max_rounds=50)
    r = run(topo, cfg)
    assert r.rounds == 50  # ran (on the chunked engine), no refusal


def test_explicit_fused_refuses_robust_agg_naming_chunked():
    # The fused tiers never implement clip/trim: engine='auto' demotes to
    # the chunked engine, an EXPLICIT fused request fails loudly naming it.
    topo = build_topology("full", 256)
    cfg = SimConfig(n=256, topology="full", algorithm="push-sum",
                    delivery="pool", byzantine_rate=0.05, robust_agg="clip",
                    engine="fused", strict_engine=True, chunk_rounds=32,
                    max_rounds=40)
    with pytest.raises(ValueError, match="chunked XLA round bodies"):
        run(topo, cfg)


# ------------------------------------------------------- serving bucketing


def test_keys_fold_byzantine_class_and_robust_agg():
    from cop5615_gossip_protocol_tpu.serving import keys

    base = dict(n=128, topology="full", algorithm="push-sum")
    honest = SimConfig(**base)
    byz = SimConfig(**base, byzantine_rate=0.1,
                    byzantine_mode="mass_inflate")
    fc = keys.fault_class(byz)
    assert any(isinstance(t, tuple) and t and t[0] == "byzantine"
               for t in fc)
    assert keys.fault_class(honest) == ("fault-free",)
    # robust_agg splits compile classes even when fault-free (the traced
    # absorb differs; the lint warns but the key must not collide).
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        clipped = SimConfig(**base, robust_agg="clip")
    assert keys.compile_class(clipped) != keys.compile_class(honest)
    # Mode changes the byzantine class.
    byz2 = SimConfig(**base, byzantine_rate=0.1,
                     byzantine_mode="mass_deflate")
    assert keys.fault_class(byz) != keys.fault_class(byz2)


# ----------------------------------------------------- telemetry + markers


def test_telemetry_reports_byzantine_count_and_trace_field():
    cfg = SimConfig(n=128, topology="full", algorithm="push-sum", seed=4,
                    byzantine_schedule="5:7", byzantine_mode="mass_inflate",
                    robust_agg="clip", telemetry=True, chunk_rounds=16,
                    max_rounds=40)
    r = run(build_topology("full", 128), cfg)
    rows = np.asarray(r.telemetry.data)
    byz_col = rows[:, telemetry_mod.COL_BYZ]
    # Zero before the onset round, exactly 7 adversaries from it on.
    nz = np.nonzero(byz_col)[0]
    assert nz.size > 0
    assert (byz_col[:nz[0]] == 0).all()
    assert (byz_col[nz[0]:] == 7).all()
    assert 4 <= nz[0] <= 6  # the onset row (round indexing convention)
    recs = r.telemetry.to_trace_records("push-sum")
    marked = [rec for rec in recs if rec.get("byzantine")]
    assert marked and all(rec["byzantine"] == 7 for rec in marked)

    # The trajectory analyzer picks up the onset and marks the curve.
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from benchmarks import trajectory

    a = trajectory.analyze(recs, population=128)
    assert a["byzantine_final"] == 7
    assert len(a["byzantine_onset_rounds"]) == 1
    curve = trajectory.ascii_curve(recs, 128)
    assert any("byzantine onsets" in ln for ln in curve)
    assert any("!" in ln for ln in curve)


def test_fused_telemetry_byzantine_column_matches_chunked():
    cfg = SimConfig(n=256, topology="ring", algorithm="gossip", seed=6,
                    byzantine_schedule="3:9", byzantine_mode="garble",
                    telemetry=True, chunk_rounds=16, max_rounds=48)
    ra, _ = _run2(dataclasses_replace(cfg, engine="chunked"))
    rb, _ = _run2(dataclasses_replace(cfg, engine="fused"))
    a = np.asarray(ra.telemetry.data)[:, telemetry_mod.COL_BYZ]
    b = np.asarray(rb.telemetry.data)[:, telemetry_mod.COL_BYZ]
    n = min(len(a), len(b))
    np.testing.assert_array_equal(a[:n], b[:n])
    assert a.max() == 9
