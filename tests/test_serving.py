"""Serving plane (ISSUE 6): canonical engine keys, the warm pool, the
heterogeneous micro-batcher's bitwise parity with one-shot runs, admission
control, the HTTP/JSONL fronts, the degradation availability story, and
the pinned batching-ratio contract."""

import json
import os
import socket
import threading
import time

import jax
import numpy as np
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.config import MAX_REPLICAS
from cop5615_gossip_protocol_tpu.models.runner import _LEADER_TAG, run
from cop5615_gossip_protocol_tpu.models.sweep import (
    LANE_FILLER_TAG0,
    REPLICA_TAG0,
    _host_key_data,
    run_batched_keys,
)
from cop5615_gossip_protocol_tpu.serving import keys as keys_mod
from cop5615_gossip_protocol_tpu.serving import pool as pool_mod
from cop5615_gossip_protocol_tpu.serving.admission import (
    AdmissionError,
    ServingStats,
)
from cop5615_gossip_protocol_tpu.serving.batcher import (
    MicroBatcher,
    lane_bucket,
)
from cop5615_gossip_protocol_tpu.serving.server import (
    ServingApp,
    config_from_request,
    make_jsonl_server,
    make_server,
)

# ------------------------------------------------------------ canonical keys


def _key_of(cfg, kind=None, n=None):
    topo = keys_mod.get_topology(kind or cfg.topology, n or cfg.n,
                                 seed=cfg.seed)
    return keys_mod.canonical_key(cfg, topo)


def test_canonical_key_seed_invariant_fault_free():
    a = _key_of(SimConfig(n=64, topology="full", algorithm="gossip", seed=0))
    b = _key_of(SimConfig(n=64, topology="full", algorithm="gossip", seed=9))
    assert a == b


def test_canonical_key_splits_on_compile_class():
    base = SimConfig(n=64, topology="full", algorithm="gossip", seed=0)
    assert _key_of(base) != _key_of(
        SimConfig(n=64, topology="full", algorithm="push-sum", seed=0)
    )
    assert _key_of(base) != _key_of(
        SimConfig(n=128, topology="full", algorithm="gossip", seed=0)
    )
    assert _key_of(base) != _key_of(
        SimConfig(n=64, topology="full", algorithm="gossip", seed=0,
                  telemetry=True)
    )
    assert _key_of(base) != _key_of(
        SimConfig(n=64, topology="full", algorithm="gossip", seed=0,
                  fault_rate=0.1)
    )


def test_canonical_key_crash_model_pins_seed():
    # The churn planes derive from PRNGKey(seed) and are BAKED into the
    # traced round body — crash-model engines must be per-seed.
    mk = lambda s: _key_of(SimConfig(  # noqa: E731
        n=64, topology="full", algorithm="gossip", seed=s,
        crash_schedule="3:8", quorum=0.9,
    ))
    assert mk(0) == mk(0)
    assert mk(0) != mk(1)


def test_fault_class_normalization_collapses_unused_knobs():
    # quorum/rejoin are only consulted under a crash model: fault-free
    # configs spelled differently must share one engine. (quorum != 1
    # without a crash model lints, so compare via the fault class.)
    with pytest.warns(RuntimeWarning):
        relaxed = SimConfig(n=64, topology="full", algorithm="gossip",
                            seed=0, quorum=0.9)
    strictq = SimConfig(n=64, topology="full", algorithm="gossip", seed=3)
    topo = keys_mod.get_topology("full", 64)
    assert keys_mod.fault_class(relaxed) == ("fault-free",)
    assert (keys_mod.canonical_key(relaxed, topo)
            == keys_mod.canonical_key(strictq, topo))
    # Explicit delta equal to the resolved default is the same program.
    a = SimConfig(n=64, topology="full", algorithm="push-sum", seed=0)
    b = SimConfig(n=64, topology="full", algorithm="push-sum", seed=0,
                  delta=a.resolved_delta)
    assert (keys_mod.canonical_key(a, topo)
            == keys_mod.canonical_key(b, topo))


def test_padded_population_buckets_by_builder_rounding():
    # grid2d rounds the request up to a square: 95 and 100 land in the
    # same padded-N bucket (and thus the same engine/batch bucket).
    assert keys_mod.padded_population("grid2d", 95) == 100
    assert keys_mod.padded_population("grid2d", 100) == 100
    cfg95 = SimConfig(n=95, topology="grid2d", algorithm="gossip", seed=0)
    cfg100 = SimConfig(n=100, topology="grid2d", algorithm="gossip", seed=1)
    t95 = keys_mod.get_topology("grid2d", 95)
    t100 = keys_mod.get_topology("grid2d", 100)
    assert (keys_mod.serve_bucket_key(cfg95, t95)
            == keys_mod.serve_bucket_key(cfg100, t100))


def test_host_key_data_matches_prngkey():
    # The serving hot path builds threefry key data on the host; a silent
    # upstream layout change must fail here, not corrupt streams.
    for s in (0, 3, 12345, 2**31, 2**32 - 1, 2**40 + 17):
        np.testing.assert_array_equal(
            _host_key_data(s), np.asarray(jax.random.PRNGKey(s)),
            err_msg=f"seed {s}",
        )
    with pytest.raises(ValueError, match="seeds"):
        _host_key_data(-1)


def test_lane_filler_tag_region_disjoint():
    # TAG MAP contract (ops/faults.py): filler tags sit above the replica
    # region and below the leader tag.
    assert LANE_FILLER_TAG0 == REPLICA_TAG0 + MAX_REPLICAS
    assert LANE_FILLER_TAG0 > 2**30  # above every round index
    hi = LANE_FILLER_TAG0 + 4096
    assert hi < _LEADER_TAG
    assert hi < 2**31


def test_seed_built_topology_values_split_the_engine_key():
    # imp2d neighbor tensors depend on the build seed; the batch engine
    # caches the DEVICE tensors alongside the compiled chunk, so two
    # same-shape imp graphs from different seeds must never share a key
    # (review finding: shape-only identity served the wrong graph).
    cfg = SimConfig(n=64, topology="imp2d", algorithm="gossip", seed=0)
    ta = build_topology("imp2d", 64, seed=0)
    tb = build_topology("imp2d", 64, seed=1)
    assert keys_mod.canonical_key(cfg, ta) != keys_mod.canonical_key(cfg, tb)
    # Same seed -> same key (fingerprint is content, not identity).
    ta2 = build_topology("imp2d", 64, seed=0)
    assert keys_mod.canonical_key(cfg, ta) == keys_mod.canonical_key(cfg, ta2)


def test_batched_imp2d_uses_each_calls_own_graph():
    # End-to-end: batch on graph A, then batch on same-shape graph B —
    # lane 0 of B's batch must match the one-shot run on B, not replay A.
    for seed in (0, 1):
        topo = build_topology("imp2d", 64, seed=seed)
        cfg = SimConfig(n=64, topology="imp2d", algorithm="gossip",
                        seed=seed)
        batch = run_batched_keys(topo, cfg, [seed], lanes=1)
        res = run(topo, cfg)
        assert batch.rounds[0] == res.rounds, f"topo seed {seed}"


# ------------------------------------------------------------------ the pool


def test_pool_lru_and_counters():
    p = pool_mod.WarmEnginePool(capacity=2)
    a, hit = p.get_or_build("a", lambda: "A")
    assert (a, hit) == ("A", False)
    a, hit = p.get_or_build("a", lambda: "A2")
    assert (a, hit) == ("A", True)  # cached build wins
    p.get_or_build("b", lambda: "B")
    p.get_or_build("a", lambda: "A3")  # refresh a's recency
    p.get_or_build("c", lambda: "C")  # evicts b (LRU)
    assert p.get_or_build("b", lambda: "B2") == ("B2", False)
    s = p.stats()
    assert s["evictions"] >= 2 and s["entries"] == 2
    assert s["hits"] == 2 and s["misses"] == 4


def test_batch_engine_reused_across_seeds():
    cfg = SimConfig(n=48, topology="full", algorithm="gossip", seed=0)
    topo = build_topology("full", 48)
    first = run_batched_keys(topo, cfg, [101, 102], lanes=2)
    again = run_batched_keys(
        topo, SimConfig(n=48, topology="full", algorithm="gossip", seed=77),
        [201, 202], lanes=2,
    )
    assert again.engine_cache == "hit"
    assert first.lanes == again.lanes == 2
    # Different lane width is a different engine variant.
    wider = run_batched_keys(topo, cfg, [1, 2, 3], lanes=4)
    assert wider.lanes == 4


# -------------------------------------------- batcher correctness (bitwise)


def _one_shot(cfg, topo):
    cap = {}

    def hook(rounds, state):
        cap["state"] = jax.tree.map(np.asarray, state)

    res = run(topo, cfg, on_chunk=hook)
    return res, cap["state"]


def test_batched_gossip_bitwise_matches_one_shot_with_filler_lanes():
    # Satellite: a bucketed mixed-config batch's per-request results
    # bitwise-match the same requests run one-shot through runner.run —
    # including when lane-count bucketing pads the batch (filler lanes
    # ride the LANE_FILLER_TAG0 region and are discarded).
    seeds = [3, 11, 42]
    topo = build_topology("full", 64, seed=3)
    cfg0 = SimConfig(n=64, topology="full", algorithm="gossip", seed=seeds[0],
                     telemetry=True)
    batch = run_batched_keys(topo, cfg0, seeds, lanes=4)
    assert batch.lanes == 4 and batch.replicas == 3
    for i, s in enumerate(seeds):
        cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=s,
                        telemetry=True)
        res, state = _one_shot(cfg, topo)
        assert batch.rounds[i] == res.rounds
        assert batch.converged[i] == res.converged
        for f in state._fields:
            np.testing.assert_array_equal(
                getattr(batch.final_states[i], f), getattr(state, f),
                err_msg=f"seed {s} field {f}",
            )
        # Telemetry demux: lane i's rows are the one-shot plane's rows.
        np.testing.assert_array_equal(
            batch.telemetry[i].data, res.telemetry.data,
            err_msg=f"seed {s} telemetry",
        )


def test_batched_pushsum_bitwise_matches_one_shot():
    seeds = [5, 6, 7]
    topo = build_topology("full", 48)
    cfg0 = SimConfig(n=48, topology="full", algorithm="push-sum",
                     seed=seeds[0], delta=1e-3)
    batch = run_batched_keys(topo, cfg0, seeds, lanes=4)
    for i, s in enumerate(seeds):
        cfg = SimConfig(n=48, topology="full", algorithm="push-sum", seed=s,
                        delta=1e-3)
        res, state = _one_shot(cfg, topo)
        assert batch.rounds[i] == res.rounds
        # STATE parity is bitwise; the derived MAE report is computed by
        # numpy host-side in the sweep vs XLA in the runner — reduction
        # order differs in the last float32 bits.
        for f in state._fields:
            np.testing.assert_array_equal(
                getattr(batch.final_states[i], f), getattr(state, f),
                err_msg=f"seed {s} field {f}",
            )
        assert batch.estimate_mae[i] == pytest.approx(res.estimate_mae,
                                                      rel=1e-5)


def test_run_batched_keys_validates_lanes():
    topo = build_topology("full", 32)
    cfg = SimConfig(n=32, topology="full", algorithm="gossip", seed=0)
    with pytest.raises(ValueError, match="at least one"):
        run_batched_keys(topo, cfg, [])
    with pytest.raises(ValueError, match="lanes"):
        run_batched_keys(topo, cfg, [1, 2, 3], lanes=2)


def test_lane_bucket():
    assert lane_bucket(1, 64, 1) == 1
    assert lane_bucket(3, 64, 1) == 4
    assert lane_bucket(3, 64, 8) == 8
    assert lane_bucket(9, 64, 8) == 16
    assert lane_bucket(100, 64, 8) == 64
    assert lane_bucket(1, 4, 8) == 4  # min clamps to max


# --------------------------------------------------------- app + admission


def _mk_app(**kw):
    kw.setdefault("window_s", 0.01)
    kw.setdefault("max_lanes", 8)
    kw.setdefault("min_lanes", 1)
    return ServingApp(**kw)


def test_serving_app_end_to_end_two_buckets():
    # Generous window: the co-batching assertion below needs all three
    # full-topology submissions inside one batching window even on a
    # noisy CI scheduler.
    app = _mk_app(window_s=0.25)
    try:
        bodies = [
            {"schema_version": 1, "n": 64, "topology": "full",
             "algorithm": "gossip", "seed": s, "telemetry": True}
            for s in range(3)
        ] + [
            {"schema_version": 1, "n": 36, "topology": "grid2d",
             "algorithm": "gossip", "seed": 9},
        ]
        results = {}

        def go(i):
            results[i] = app.handle_run(bodies[i])

        threads = [threading.Thread(target=go, args=(i,))
                   for i in range(len(bodies))]
        # Poll /stats WHILE requests are in flight: snapshot() and
        # submit() take the stats and queue locks in opposite orders, so
        # a lock inversion would deadlock this test (review finding).
        polling = {"stop": False}

        def poll():
            while not polling["stop"]:
                app.snapshot()
                time.sleep(0.005)

        poller = threading.Thread(target=poll)
        poller.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        polling["stop"] = True
        poller.join()
        for _i, (status, resp) in results.items():
            assert status == 200, resp
            assert resp["ok"] and resp["result"]["outcome"] == "converged"
            assert resp["serving"]["engine_degraded"] is None
            assert resp["serving"]["batch_occupancy"] >= 1
            assert any(e["event"] == "batch-dispatched"
                       for e in resp["events"])
        # The full-topology trio co-batched (same bucket, one window).
        occ = [r["serving"]["batch_occupancy"]
               for (st, r) in results.values()
               if r["result"]["topology"] == "full"]
        assert max(occ) == 3
        # Telemetry demux: each full-bucket response carries ITS rows.
        for i in range(3):
            _, resp = results[i]
            traj = resp["telemetry"]
            assert len(traj) == resp["result"]["rounds"]
            assert (traj[-1]["converged_count"]
                    == resp["result"]["converged_count"])
        snap = app.snapshot()
        assert snap["received"] == snap["admitted"] == 4
        assert snap["completed"] == 4 and snap["failed"] == 0
        assert snap["batched_requests"] == 4
        assert len(snap["buckets"]) == 2
        assert snap["service_ms_p99"] is not None
    finally:
        app.close()


def test_handle_batch_envelope_preserves_order_and_slots_errors():
    app = _mk_app()
    try:
        status, resp = app.handle_batch({"requests": [
            {"schema_version": 1, "n": 32, "topology": "full",
             "algorithm": "gossip", "seed": 1},
            {"schema_version": 1, "n": 32, "topology": "full",
             "algorithm": "gossip", "seed": 2,
             "params": {"n_devices": 2}},  # invalid: slot-level 400
            {"schema_version": 1, "n": 32, "topology": "full",
             "algorithm": "gossip", "seed": 3},
        ]})
        assert status == 200 and resp["ok"]
        st = [m["status"] for m in resp["responses"]]
        assert st == [200, 400, 200]
        assert resp["responses"][1]["error"] == "invalid-config"
        assert (resp["responses"][0]["result"]["rounds"] > 0)
        status, resp = app.handle_batch({"requests": []})
        assert status == 400
        status, resp = app.handle_batch({"nope": 1})
        assert status == 400
    finally:
        app.close()


def test_admission_bounded_queue_rejects():
    stats = ServingStats()
    b = MicroBatcher(stats=stats, queue_limit=2, min_lanes=1)
    # NOT started: submissions stay queued, so the bound is observable.
    b.submit(SimConfig(n=32, topology="full", algorithm="gossip",
                       seed=0, engine="chunked"), False)
    b.submit(SimConfig(n=32, topology="full", algorithm="gossip",
                       seed=1, engine="chunked"), False)
    with pytest.raises(AdmissionError) as e:
        b.submit(SimConfig(n=32, topology="full", algorithm="gossip",
                           seed=2, engine="chunked"), False)
    assert e.value.queue_depth == 2 and e.value.queue_limit == 2
    assert stats._depth_fn() == 2
    b.stop(drain=False)
    assert stats.failed == 2  # undispatched requests failed structurally


def test_invalid_configs_are_structured_400s():
    app = _mk_app(max_n=1000)
    try:
        for body, marker in [
            ({"n": 64, "topology": "full"}, "missing"),
            ({"n": 64, "topology": "nope", "algorithm": "gossip"},
             "unknown topology"),
            ({"n": 64, "topology": "full", "algorithm": "gossip",
              "params": {"stall_chunks": 2}}, "unsupported params"),
            ({"n": 5000, "topology": "full", "algorithm": "gossip"},
             "population cap"),
            ({"n": 64, "topology": "full", "algorithm": "gossip",
              "schema_version": 99}, "newer"),
            ({"n": 64, "topology": "full", "algorithm": "gossip",
              "params": {"quorum": 2.0}}, "quorum"),
            # Wrong-TYPED param values raise TypeError inside SimConfig
            # validation ("0.0 <= '0.1'") — still a structured 400, never
            # a dropped connection (review finding).
            ({"n": 64, "topology": "full", "algorithm": "gossip",
              "params": {"fault_rate": "0.1"}}, None),
        ]:
            status, resp = app.handle_run(body)
            assert status == 400, body
            assert resp["error"] == "invalid-config"
            if marker is not None:
                assert marker in resp["detail"], (marker, resp["detail"])
        snap = app.snapshot()
        assert snap["invalid"] == 7
        assert snap["received"] == (
            snap["admitted"] + snap["rejected"] + snap["invalid"]
        )
    finally:
        app.close()


def test_degraded_batch_walks_to_one_shot_never_500(monkeypatch):
    # Availability story: an environmental failure of the vmapped batch
    # engine degrades to per-request one-shot runs with a structured
    # engine_degraded field — never an opaque failure.
    monkeypatch.setenv("GOSSIP_TPU_STRICT_ENGINE", "0")
    from cop5615_gossip_protocol_tpu.models import sweep as sweep_mod

    def boom(*a, **k):
        raise RuntimeError("injected RESOURCE_EXHAUSTED: vmem")

    # The continuous executor (ISSUE 14, default) runs serve_lanes; the
    # wave path (probe / --no-continuous) runs run_batched_keys — patch
    # both so the injection holds whichever path dispatches.
    monkeypatch.setattr(sweep_mod, "serve_lanes", boom)
    monkeypatch.setattr(sweep_mod, "run_batched_keys", boom)
    app = _mk_app()
    try:
        status, resp = app.handle_run(
            {"schema_version": 1, "n": 32, "topology": "full",
             "algorithm": "gossip", "seed": 4, "telemetry": True}
        )
        assert status == 200, resp
        walk = resp["serving"]["engine_degraded"]
        assert walk and walk[0]["from"] == "batched-vmap"
        assert "injected" in walk[0]["reason"]
        assert resp["result"]["outcome"] == "converged"
        assert len(resp["telemetry"]) == resp["result"]["rounds"]
        snap = app.snapshot()
        assert snap["degraded"] == 1
        # The occupancy identity must survive the degraded path (the
        # one-shot walk counts its own single-lane batch — no double
        # count from the failed vmapped attempt; review finding).
        assert snap["batched_requests"] == snap["completed"] + snap["failed"]
    finally:
        app.close()


def test_degraded_batch_strict_mode_is_structured_503(monkeypatch):
    monkeypatch.setenv("GOSSIP_TPU_STRICT_ENGINE", "1")
    from cop5615_gossip_protocol_tpu.models import sweep as sweep_mod

    boom = lambda *a, **k: (_ for _ in ()).throw(RuntimeError("env down"))  # noqa: E731
    monkeypatch.setattr(sweep_mod, "serve_lanes", boom)
    monkeypatch.setattr(sweep_mod, "run_batched_keys", boom)
    app = _mk_app()
    try:
        status, resp = app.handle_run(
            {"schema_version": 1, "n": 32, "topology": "full",
             "algorithm": "gossip", "seed": 4}
        )
        assert status == 503
        assert resp["error"] == "engine-unavailable"
        assert "env down" in resp["detail"]
        snap = app.snapshot()
        assert snap["failed"] == 1 and snap["completed"] == 0
    finally:
        app.close()


def test_executor_survives_unexpected_engine_exception(monkeypatch):
    # A poison request whose execution raises OUTSIDE the degradation
    # vocabulary must fail structurally and leave the executor alive for
    # the next request (review finding: a dead executor thread is a
    # one-request denial of service).
    from cop5615_gossip_protocol_tpu.models import sweep as sweep_mod

    real = sweep_mod.serve_lanes
    state = {"boom": True}

    def flaky(*a, **k):
        if state["boom"]:
            state["boom"] = False
            raise OverflowError("Python int too large to convert to C long")
        return real(*a, **k)

    monkeypatch.setattr(sweep_mod, "serve_lanes", flaky)
    app = _mk_app()
    try:
        status, resp = app.handle_run(
            {"schema_version": 1, "n": 32, "topology": "full",
             "algorithm": "gossip", "seed": 1}
        )
        assert status == 503 and resp["error"] == "internal-error"
        assert "OverflowError" in resp["detail"]
        status, resp = app.handle_run(
            {"schema_version": 1, "n": 32, "topology": "full",
             "algorithm": "gossip", "seed": 2}
        )
        assert status == 200 and resp["result"]["outcome"] == "converged"
        snap = app.snapshot()
        assert snap["batched_requests"] == snap["completed"] + snap["failed"]
    finally:
        app.close()


def test_request_seed_bounded_at_validation():
    app = _mk_app()
    try:
        for bad in (-1, 2**32, 2**80, "7"):
            status, resp = app.handle_run(
                {"schema_version": 1, "n": 32, "topology": "full",
                 "algorithm": "gossip", "seed": bad}
            )
            assert status == 400 and "seed" in resp["detail"], bad
    finally:
        app.close()


# ------------------------------------------------------- HTTP + JSONL fronts


def test_http_and_jsonl_round_trip():
    app = _mk_app()
    httpd = make_server(app, "127.0.0.1", 0)
    jsonld = make_jsonl_server(app, "127.0.0.1", 0)
    for srv in (httpd, jsonld):
        threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        import http.client

        host, port = httpd.server_address[:2]
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("POST", "/run", json.dumps(
            {"schema_version": 1, "n": 32, "topology": "full",
             "algorithm": "push-sum", "seed": 2}
        ), {"Content-Type": "application/json"})
        r = conn.getresponse()
        payload = json.loads(r.read())
        assert r.status == 200 and payload["ok"]
        assert payload["result"]["estimate_mae"] is not None
        assert payload["schema_version"] == 1
        conn.request("GET", "/healthz")
        assert conn.getresponse().read() == b'{"ok": true}'
        conn.request("GET", "/stats")
        stats = json.loads(conn.getresponse().read())
        assert stats["completed"] >= 1
        conn.request("GET", "/nope")
        assert conn.getresponse().status == 404
        conn.close()

        jhost, jport = jsonld.server_address[:2]
        sock = socket.create_connection((jhost, jport), timeout=60)
        rfile = sock.makefile("rb")
        # Single request line, then a multi-user envelope line.
        sock.sendall(json.dumps(
            {"schema_version": 1, "n": 32, "topology": "full",
             "algorithm": "gossip", "seed": 5}
        ).encode() + b"\n")
        one = json.loads(rfile.readline())
        assert one["status"] == 200 and one["ok"]
        sock.sendall(json.dumps({"requests": [
            {"schema_version": 1, "n": 32, "topology": "full",
             "algorithm": "gossip", "seed": s} for s in (6, 7)
        ]}).encode() + b"\n")
        env = json.loads(rfile.readline())
        assert env["status"] == 200 and len(env["responses"]) == 2
        assert all(m["status"] == 200 for m in env["responses"])
        sock.sendall(b"not json\n")
        bad = json.loads(rfile.readline())
        assert bad["status"] == 400 and bad["error"] == "invalid-json"
        rfile.close()
        sock.close()
    finally:
        for srv in (httpd, jsonld):
            srv.shutdown()
            srv.server_close()
        app.close()


# ----------------------------------------------------- pinned batching ratio


def test_batching_beats_batching_off_control_pinned():
    """The micro-batcher's reason to exist, pinned: serving K same-bucket
    requests as vmapped batches beats serving them one program at a time
    (same warm pool both ways). Floor env-overridable:
    GOSSIP_TPU_SERVE_BATCH_RATIO (default 1.3).

    The K requests ride ONE /batch envelope (admitted together, awaited
    together) instead of K client threads: on the 2-core CI box, K thread
    spawns plus their GIL churn cost more wall than the engine difference
    under measurement, which made the old thread-per-request form flake —
    the envelope isolates the server-side batching win the pin is about.
    K exceeds max_lanes so the batching window closes early on every wave
    (a sub-width backlog waits out the full window, which the control —
    batching off — never pays; comparing those two measured the window,
    not the batching)."""
    floor = float(os.environ.get("GOSSIP_TPU_SERVE_BATCH_RATIO", "") or 1.3)
    K = 96
    bodies = [
        {"schema_version": 1, "n": 32, "topology": "full",
         "algorithm": "gossip", "seed": 1000 + s, "params":
         {"rumor_threshold": 5}}
        for s in range(K)
    ]

    def serve_all(app):
        t0 = time.perf_counter()
        status, resp = app.handle_batch(
            {"requests": [dict(b) for b in bodies]}
        )
        wall = time.perf_counter() - t0
        assert status == 200, resp
        assert all(m["status"] == 200 for m in resp["responses"]), resp
        return wall

    # min_lanes == max_lanes pins ONE compiled width for the batched app,
    # so occupancy jitter between passes can never trigger a mid-
    # measurement compile.
    batched_app = _mk_app(max_lanes=32, min_lanes=32, window_s=0.02)
    control_app = ServingApp(window_s=0.02, max_lanes=32, min_lanes=1,
                             batching=False)
    try:
        # Warm both paths (compile is process state, not steady state),
        # then best-of-3 to shed scheduler noise.
        serve_all(batched_app)
        serve_all(control_app)
        batched = min(serve_all(batched_app) for _ in range(3))
        control = min(serve_all(control_app) for _ in range(3))
    finally:
        batched_app.close()
        control_app.close()
    ratio = control / batched
    assert ratio >= floor, (
        f"batching speedup {ratio:.2f}x under the floor {floor}x "
        f"(batched {batched * 1e3:.0f} ms vs control {control * 1e3:.0f} ms "
        f"for {K} requests)"
    )


# ---------------------------------------------------------- request parsing


def test_config_from_request_forces_chunked_engine():
    cfg, tele, priority, deadline_ms = config_from_request(
        {"schema_version": 1, "n": 64, "topology": "2D",
         "algorithm": "pushsum", "telemetry": True,
         "params": {"quorum": 0.9, "crash_rate": 0.01}},
        65536,
    )
    assert cfg.engine == "chunked"
    assert cfg.topology == "grid2d" and cfg.algorithm == "push-sum"
    assert tele is True and cfg.telemetry is True
    assert cfg.crash_model
    # v1 requests carry no resilience fields: defaults apply.
    assert priority == "batch" and deadline_ms is None


def test_config_from_request_resilience_fields():
    cfg, _, priority, deadline_ms = config_from_request(
        {"schema_version": 2, "n": 32, "topology": "full",
         "algorithm": "gossip", "priority": "interactive",
         "deadline_ms": 1500},
        65536,
    )
    assert priority == "interactive" and deadline_ms == 1500.0
    for bad in (
        {"priority": "urgent"},
        {"deadline_ms": 0},
        {"deadline_ms": -5},
        {"deadline_ms": "soon"},
        {"deadline_ms": True},
    ):
        with pytest.raises(ValueError):
            config_from_request(
                {"schema_version": 2, "n": 32, "topology": "full",
                 "algorithm": "gossip", **bad},
                65536,
            )
