"""Global-residual termination inside the fused engines (VERDICT r3 #5).

Every push-sum Pallas engine implements the global criterion in-kernel:
per round, the tile absorb accumulates the count of nodes whose relative
ratio change exceeds delta * max(|ratio|, 1); a zero count fires the
all-or-nothing conv latch and stops the chunk. Oracle: the chunked XLA
path with termination='global' (models/pushsum.absorb global branch) —
round counts must match exactly and converged_count must be exactly n
(pad lanes never latch).

Engines are forced at small populations the same way their own test files
do: budget/cap monkeypatches, interpret mode off-TPU.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import fused_pool, fused_stencil

# Interpret-mode Pallas oracle: bitwise engine validation that cannot
# fit the ROADMAP tier-1 wall-clock budget on a CPU-only container (the
# kernels run under the Pallas interpreter). Full-suite / TPU runs
# execute it: `pytest tests/` (no -m filter) or `pytest -m slow`.
pytestmark = pytest.mark.slow


def _run_pair(kind, n, fused_kw=None, **kw):
    kw.setdefault("algorithm", "push-sum")
    kw.setdefault("termination", "global")
    kw.setdefault("max_rounds", 200000)
    kw.setdefault("chunk_rounds", 64)
    topo = build_topology(kind, n)
    a = run(topo, SimConfig(n=n, topology=kind, engine="chunked", **kw))
    b = run(topo, SimConfig(n=n, topology=kind, engine="fused",
                            **{**kw, **(fused_kw or {})}))
    return topo, a, b


def _assert_match(topo, a, b):
    assert a.converged and b.converged
    assert a.rounds == b.rounds, (a.rounds, b.rounds)
    assert a.converged_count == topo.n
    assert b.converged_count == topo.n
    assert abs(a.estimate_mae - b.estimate_mae) < 1e-3


def test_global_fused_stencil_matches_chunked():
    # v1 whole-array engine: torus3d 8^3 (wrap, 512 % 128 == 0).
    topo, a, b = _run_pair("torus3d", 512)
    _assert_match(topo, a, b)


def test_global_fused_stencil_padded_nonwrap():
    # v1 at n % 128 != 0 on a non-wrap lattice: pad lanes (w=1, inbox 0)
    # must neither block the verdict nor count as converged.
    topo, a, b = _run_pair("grid3d", 729)
    _assert_match(topo, a, b)


def test_global_fused_stencil2_matches_chunked():
    # 1000 % 128 != 0 on a wrap topology: v1 refuses, stencil2 serves.
    topo, a, b = _run_pair("torus3d", 1000)
    _assert_match(topo, a, b)


def test_global_fused_stencil_hbm_matches_chunked(monkeypatch):
    monkeypatch.setattr(fused_stencil, "_VMEM_BUDGET", 1000)
    topo, a, b = _run_pair("torus3d", 1000)
    _assert_match(topo, a, b)


def test_global_fused_pool_matches_chunked():
    topo, a, b = _run_pair("full", 1024, delivery="pool")
    _assert_match(topo, a, b)


def test_global_fused_pool2_matches_chunked(monkeypatch):
    monkeypatch.setattr(fused_pool, "MAX_POOL_NODES", 1000)
    topo, a, b = _run_pair("full", 2048, delivery="pool")
    _assert_match(topo, a, b)


def test_global_fused_imp_matches_chunked():
    topo, a, b = _run_pair("imp3d", 729, delivery="pool")
    _assert_match(topo, a, b)


def test_global_fused_resume_at_convergence_runs_zero_rounds():
    # A checkpoint taken at convergence must execute zero further rounds:
    # the kernel seeds its done flag from the incoming conv plane, which
    # in global mode is the latched all-ones plane.
    n = 512
    topo = build_topology("torus3d", n)
    cfg = SimConfig(n=n, topology="torus3d", algorithm="push-sum",
                    termination="global", engine="fused",
                    max_rounds=200000, chunk_rounds=64)
    full = run(topo, cfg)
    assert full.converged
    final = {}
    run(topo, cfg, on_chunk=lambda r, s: final.update(state=s, rounds=r))
    resumed = run(topo, cfg, start_state=jax.tree.map(jnp.asarray, final["state"]),
                  start_round=final["rounds"])
    assert resumed.rounds == full.rounds
    assert resumed.converged_count == n


def test_global_auto_dispatch_uses_fused_on_tpu_only():
    # auto + global on CPU stays on the chunked path (compiled engines are
    # TPU-only in auto mode); explicit fused runs interpreted. Both give
    # the same rounds — this pins that auto did not silently change.
    n = 512
    topo = build_topology("torus3d", n)
    base = dict(n=n, topology="torus3d", algorithm="push-sum",
                termination="global", max_rounds=200000)
    r_auto = run(topo, SimConfig(engine="auto", **base))
    r_chunked = run(topo, SimConfig(engine="chunked", **base))
    assert r_auto.rounds == r_chunked.rounds


# Sharded fused + termination='global' (VERDICT r4 #8) is covered where the
# compositions live: tests/test_fused_sharded.py and
# tests/test_fused_hbm_sharded.py run the psum'd per-round unstable vector +
# capped-rerun exact stop against the chunked sharded global oracle, through
# the runner dispatch; tests/test_pushsum.py pins the no-plan raise.
