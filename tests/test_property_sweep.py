"""Property sweep (SURVEY.md §4): every topology x algorithm converges at
random small populations and seeds, in both semantics modes.

The reference's entire validation story was eight manual timed runs
(report.pdf p.2-3); this sweep is the systematic version: for each of the 9
topology builders and both protocols, three (n, seed) draws must converge
with every live node accounted for, push-sum estimates near the true mean,
and the run result internally consistent. Catches regressions that
per-feature tests anchored to fixed seeds can miss (e.g. a topology builder
edge case at an awkward population).
"""

import numpy as np
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
from cop5615_gossip_protocol_tpu.config import TOPOLOGIES

_RNG = np.random.RandomState(20260730)
_CASES = [
    (kind, algo, int(_RNG.randint(20, 400)), int(_RNG.randint(0, 1 << 16)))
    for kind in TOPOLOGIES
    for algo in ("gossip", "push-sum")
    for _ in range(3)
]


@pytest.mark.parametrize("kind,algo,n,seed", _CASES)
def test_converges_everywhere(kind, algo, n, seed):
    topo = build_topology(kind, n, seed=seed)
    cfg = SimConfig(n=n, topology=kind, algorithm=algo, seed=seed,
                    max_rounds=200_000, chunk_rounds=512)
    r = run(topo, cfg)
    assert r.converged, (kind, algo, n, seed, r.rounds)
    assert r.converged_count >= r.target_count
    assert 0 < r.rounds <= 200_000
    assert r.population == topo.n
    if algo == "push-sum":
        # Converged estimates sit near the true mean (pop-1)/2 on graphs
        # that mix; 1-D graphs (line, and ref2d/ring which are line-wired)
        # stabilize locally with O(tens-of-units) error — the same
        # criterion and failure mode as the reference's delta test, so only
        # a sanity bound applies there.
        if kind in ("line", "ref2d", "ring"):
            assert r.estimate_mae < topo.n, (kind, n, seed)
        else:
            assert r.estimate_mae < max(0.05 * topo.n, 5.0), (kind, n, seed)


def test_reference_semantics_sweep():
    # The quirk-faithful mode across the reference's own CLI surface
    # (line/full/2D/Imp3D), one small draw each.
    for spelling in ("line", "full", "2D", "Imp3D"):
        from cop5615_gossip_protocol_tpu.config import normalize_topology

        kind = normalize_topology(spelling, "reference")
        n = int(_RNG.randint(20, 120))
        topo = build_topology(kind, n, semantics="reference")
        cfg = SimConfig(n=n, topology=kind, algorithm="gossip",
                        semantics="reference", max_rounds=200_000)
        r = run(topo, cfg)
        assert r.converged, (spelling, n)
        assert r.target_count <= r.population  # Q1: N of N+1
