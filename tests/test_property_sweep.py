"""Property sweep (SURVEY.md §4): every topology x algorithm converges at
random small populations and seeds, in both semantics modes.

The reference's entire validation story was eight manual timed runs
(report.pdf p.2-3); this sweep is the systematic version: for each of the 9
topology builders and both protocols, three (n, seed) draws must converge
with every live node accounted for, push-sum estimates near the true mean,
and the run result internally consistent. Catches regressions that
per-feature tests anchored to fixed seeds can miss (e.g. a topology builder
edge case at an awkward population).
"""

import numpy as np
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
from cop5615_gossip_protocol_tpu.config import TOPOLOGIES

_RNG = np.random.RandomState(20260730)
_CASES = [
    (kind, algo, int(_RNG.randint(20, 400)), int(_RNG.randint(0, 1 << 16)))
    for kind in TOPOLOGIES
    for algo in ("gossip", "push-sum")
    for _ in range(3)
]


@pytest.mark.parametrize("kind,algo,n,seed", _CASES)
def test_converges_everywhere(kind, algo, n, seed):
    topo = build_topology(kind, n, seed=seed)
    cfg = SimConfig(n=n, topology=kind, algorithm=algo, seed=seed,
                    max_rounds=200_000, chunk_rounds=512)
    r = run(topo, cfg)
    assert r.converged, (kind, algo, n, seed, r.rounds)
    assert r.converged_count >= r.target_count
    assert 0 < r.rounds <= 200_000
    assert r.population == topo.n
    if algo == "push-sum":
        # Converged estimates sit near the true mean (pop-1)/2 on graphs
        # that mix; 1-D graphs (line, and ref2d/ring which are line-wired)
        # stabilize locally with O(tens-of-units) error — the same
        # criterion and failure mode as the reference's delta test, so only
        # a sanity bound applies there.
        if kind in ("line", "ref2d", "ring"):
            assert r.estimate_mae < topo.n, (kind, n, seed)
        else:
            assert r.estimate_mae < max(0.05 * topo.n, 5.0), (kind, n, seed)


def test_reference_semantics_sweep():
    # The quirk-faithful mode across the reference's own CLI surface
    # (line/full/2D/Imp3D), one small draw each.
    for spelling in ("line", "full", "2D", "Imp3D"):
        from cop5615_gossip_protocol_tpu.config import normalize_topology

        kind = normalize_topology(spelling, "reference")
        n = int(_RNG.randint(20, 120))
        topo = build_topology(kind, n, semantics="reference")
        cfg = SimConfig(n=n, topology=kind, algorithm="gossip",
                        semantics="reference", max_rounds=200_000)
        r = run(topo, cfg)
        assert r.converged, (spelling, n)
        assert r.target_count <= r.population  # Q1: N of N+1


_ENGINE_CASES = [
    # Random (topology, algorithm, n, seed, chunk_rounds, suppress) draws —
    # fused (interpret) vs chunked differential, beyond the fixed anchors in
    # test_fused*.py. Pool cases cover the implicit full topology.
    (str(_RNG.choice(["line", "ring", "grid2d", "torus3d", "ref2d"])),
     str(_RNG.choice(["gossip", "push-sum"])),
     int(_RNG.randint(30, 700)),
     int(_RNG.randint(0, 1 << 16)),
     int(_RNG.randint(3, 40)),
     bool(_RNG.randint(0, 2)))
    for _ in range(8)
] + [
    ("full", str(_RNG.choice(["gossip", "push-sum"])),
     int(_RNG.randint(30, 700)), int(_RNG.randint(0, 1 << 16)),
     int(_RNG.randint(3, 40)), bool(_RNG.randint(0, 2)))
    for _ in range(4)
]


@pytest.mark.parametrize("kind,algo,n,seed,chunk,supp", _ENGINE_CASES)
def test_fused_matches_chunked_random_configs(kind, algo, n, seed, chunk, supp):
    # Differential fuzz: on every eligible random config, the fused Pallas
    # engine (interpret mode off-TPU) must reproduce the chunked XLA
    # engine's result — bitwise for gossip's integer state (rounds and
    # converged counts equal), rounds-exact with matching estimate quality
    # for push-sum. Ineligible draws assert the loud refusal instead.
    from cop5615_gossip_protocol_tpu.ops import fused, fused_pool, fused_stencil

    delivery = "pool" if kind == "full" else "auto"
    base = dict(n=n, topology=kind, algorithm=algo, seed=seed,
                chunk_rounds=chunk, max_rounds=100_000, delivery=delivery,
                suppress_converged=supp if algo == "gossip" else None)
    topo = build_topology(kind, n, seed=seed)
    cfg_f = SimConfig(**base, engine="fused")
    if kind == "full":
        reason = fused_pool.pool_fused_support(topo, cfg_f)
    else:
        reason = fused.fused_support(topo, cfg_f) and \
            fused_stencil.stencil2_support(topo, cfg_f)
    if reason is not None:
        with pytest.raises(ValueError, match="engine='fused' unavailable"):
            run(topo, cfg_f)
        return
    r_f = run(topo, cfg_f)
    r_c = run(topo, SimConfig(**base, engine="chunked"))
    assert r_f.rounds == r_c.rounds, (kind, algo, n, seed, chunk, supp)
    assert r_f.converged_count == r_c.converged_count
    assert r_f.converged == r_c.converged
    if algo == "push-sum":
        assert r_f.estimate_mae == pytest.approx(r_c.estimate_mae, abs=1e-3)
