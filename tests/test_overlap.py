"""Collective/compute overlap schedule (parallel/overlap.py + the batched
wires in parallel/halo.py).

Pins the tentpole contracts:
- the batched halo wire delivers bitwise the per-class schedule's values in
  the per-class accumulation order (delivery-level and end-to-end);
- the batched plane exchange / plane gather are bitwise the per-plane
  collectives (bitcast packing is exact for 32-bit planes);
- the deferred-verdict super-step loop stops at EXACTLY the serial
  schedule's round with the serial schedule's state — mid-dispatch fire,
  dispatch-boundary fire, overshoot entry, round_end exit — and composes
  with the pipelined driver's overshoot contract;
- end-to-end: the chunked sharded engine and the fused pool composition
  produce identical trajectories with the schedule on and off, including a
  crash-schedule run (the quorum verdict path).

The fused lattice compositions' own on/off parity runs in the slow
interpret-mode suites (tests/test_fused_sharded.py,
tests/test_fused_hbm_sharded.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
from cop5615_gossip_protocol_tpu.parallel import halo, overlap
from cop5615_gossip_protocol_tpu.parallel.mesh import NODE_AXIS, make_mesh
from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded
from cop5615_gossip_protocol_tpu.utils import compat


# --- batched wires: delivery-level bitwise parity --------------------------


@pytest.mark.parametrize("kind,n", [("torus3d", 512), ("line", 1001),
                                    ("grid2d", 1024)])
def test_deliver_halo_batched_bitwise(kind, n):
    topo = build_topology(kind, n)
    plan = halo.plan_halo(topo, 8)
    assert plan is not None
    rng = np.random.default_rng(0)
    vals = rng.standard_normal((2, plan.n_pad)).astype(np.float32)
    # Realistic displacements: each sender uses one of the topology's own
    # modular classes (others deliver nothing, also exercised).
    disp = rng.choice(
        np.concatenate([plan.offsets_mod, [0]]), size=plan.n_pad
    ).astype(np.int64)
    mesh = make_mesh(8)

    def f(v_loc, d_loc, batched):
        return halo.deliver_halo(v_loc, d_loc, plan, NODE_AXIS,
                                 batched=batched)

    outs = {}
    for batched in (False, True):
        fn = jax.jit(
            compat.shard_map(
                lambda v, d, b=batched: f(v, d, b), mesh=mesh,
                in_specs=(P(None, NODE_AXIS), P(NODE_AXIS)),
                out_specs=P(None, NODE_AXIS),
            )
        )
        outs[batched] = np.asarray(fn(vals, disp))
    np.testing.assert_array_equal(outs[True], outs[False])


def test_deliver_halo_batched_single_device():
    topo = build_topology("torus3d", 512)
    plan = halo.plan_halo(topo, 1)
    rng = np.random.default_rng(1)
    vals = jnp.asarray(rng.standard_normal((512,)).astype(np.float32))
    disp = jnp.asarray(rng.choice(plan.offsets_mod, size=512))
    a = halo.deliver_halo(vals, disp, plan, NODE_AXIS, batched=False)
    b = halo.deliver_halo(vals, disp, plan, NODE_AXIS, batched=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_exchange_rows_batched_bitwise_mixed_dtypes():
    # The compositions' halo exchange: mixed f32/i32 planes ride one
    # bitcast-packed ppermute pair; result must equal per-plane exchange.
    n_dev, rows_loc, H, LANES = 8, 16, 3, 8
    mesh = make_mesh(n_dev)
    rng = np.random.default_rng(2)
    p_f = rng.standard_normal((n_dev * rows_loc, LANES)).astype(np.float32)
    p_i = rng.integers(-5, 5, (n_dev * rows_loc, LANES)).astype(np.int32)

    perm_fwd = [(d, (d + 1) % n_dev) for d in range(n_dev)]
    perm_bwd = [(d, (d - 1) % n_dev) for d in range(n_dev)]

    def serial(planes):
        def ext(x):
            left = lax.ppermute(x[-H:], NODE_AXIS, perm_fwd)
            right = lax.ppermute(x[:H], NODE_AXIS, perm_bwd)
            return jnp.concatenate([left, x, right], axis=0)

        return tuple(ext(p) for p in planes)

    def batched(planes):
        return halo.exchange_rows_batched(planes, H, NODE_AXIS, n_dev)

    for f in (serial, batched):
        fn = jax.jit(
            compat.shard_map(
                lambda a, b, f=f: f((a, b)), mesh=mesh,
                in_specs=(P(NODE_AXIS), P(NODE_AXIS)),
                out_specs=(P(NODE_AXIS), P(NODE_AXIS)),
            )
        )
        ext_f, ext_i = fn(p_f, p_i)
        if f is serial:
            want = (np.asarray(ext_f), np.asarray(ext_i))
        else:
            np.testing.assert_array_equal(np.asarray(ext_f), want[0])
            np.testing.assert_array_equal(np.asarray(ext_i), want[1])
            assert ext_f.dtype == jnp.float32 and ext_i.dtype == jnp.int32


def test_gather_rows_batched_bitwise():
    n_dev, rows_loc, LANES = 8, 4, 8
    mesh = make_mesh(n_dev)
    rng = np.random.default_rng(3)
    p_f = rng.standard_normal((n_dev * rows_loc, LANES)).astype(np.float32)
    p_i = rng.integers(0, 9, (n_dev * rows_loc, LANES)).astype(np.int32)

    def serial(planes):
        return tuple(
            lax.all_gather(p, NODE_AXIS, axis=0, tiled=True) for p in planes
        )

    def batched(planes):
        return halo.gather_rows_batched(planes, NODE_AXIS)

    got = {}
    for name, f in (("serial", serial), ("batched", batched)):
        fn = jax.jit(
            compat.shard_map(
                lambda a, b, f=f: f((a, b)), mesh=mesh,
                in_specs=(P(NODE_AXIS), P(NODE_AXIS)),
                out_specs=(P(), P()),
            )
        )
        got[name] = tuple(np.asarray(x) for x in fn(p_f, p_i))
    np.testing.assert_array_equal(got["serial"][0], got["batched"][0])
    np.testing.assert_array_equal(got["serial"][1], got["batched"][1])


# --- the deferred-verdict super-step loop ---------------------------------


def _toy_loops(n_dev=8, n_loc=4, cr=3, target=17):
    """A miniature super-step engine under shard_map: each super-step adds
    1 to every slot for up to ``cr`` rounds (capped at round_end) and
    reports the local count of slots >= 8 as its metric — enough structure
    to land the verdict at any super-step and mid-dispatch. Returns
    (serial_fn, overlapped_fn) jitted over (planes, rnd, done, round_end).
    """
    mesh = make_mesh(n_dev)

    def compute(ext, rnd, cap):
        (x,) = ext
        executed = jnp.minimum(jnp.int32(cr), cap - rnd).astype(jnp.int32)
        out = x[1:-1] + executed.astype(jnp.float32)
        metric = jnp.sum((out >= 8).astype(jnp.int32))
        return (out,), executed, metric

    def exchange(planes):
        (x,) = planes
        perm_f = [(d, (d + 1) % n_dev) for d in range(n_dev)]
        perm_b = [(d, (d - 1) % n_dev) for d in range(n_dev)]
        left = lax.ppermute(x[-1:], NODE_AXIS, perm_f)
        right = lax.ppermute(x[:1], NODE_AXIS, perm_b)
        return (jnp.concatenate([left, x, right]),)

    def serial(planes, rnd, done, round_end):
        def cond(c):
            return jnp.logical_and(~c[2], c[1] < round_end)

        def body(c):
            planes, rnd, _ = c
            out, executed, metric = compute(exchange(planes), rnd, round_end)
            total = lax.psum(metric, NODE_AXIS)
            return (out, rnd + executed, total >= target)

        return lax.while_loop(cond, body, (planes, rnd, done))

    def overlapped(planes, rnd, done, round_end):
        return overlap.overlapped_superstep_loop(
            planes, rnd, done, round_end,
            exchange=exchange, compute=compute,
            psum_metric=lambda m: lax.psum(m, NODE_AXIS), target=target,
        )

    def jit_of(f):
        return jax.jit(
            compat.shard_map(
                f, mesh=mesh,
                in_specs=((P(NODE_AXIS),), P(), P(), P()),
                out_specs=((P(NODE_AXIS),), P(), P()),
            ),
            static_argnames=(),
        )

    return jit_of(serial), jit_of(overlapped), n_dev * n_loc


def test_overlapped_loop_matches_serial_all_fire_rounds():
    # Sweep initial states so the verdict fires at the 1st, 2nd, ..., super-
    # step, mid-dispatch and at the dispatch boundary: state, rounds, and
    # done must match the serial schedule exactly every time.
    serial, overlapped, n = _toy_loops()
    for x0 in range(0, 9):
        for round_end in (1, 3, 6, 7, 9, 12):
            planes = (np.full(n, float(x0), np.float32),)
            a = serial(planes, jnp.int32(0), jnp.bool_(False),
                       jnp.int32(round_end))
            b = overlapped(planes, jnp.int32(0), jnp.bool_(False),
                           jnp.int32(round_end))
            assert int(a[1]) == int(b[1]), (x0, round_end)
            assert bool(a[2]) == bool(b[2]), (x0, round_end)
            np.testing.assert_array_equal(
                np.asarray(a[0][0]), np.asarray(b[0][0])
            )


def test_overlapped_loop_overshoot_noop():
    # done_in=True: zero super-steps, planes bitwise-unchanged — the
    # models/pipeline.py overshoot contract the speculative driver needs.
    serial, overlapped, n = _toy_loops()
    planes = (np.arange(n, dtype=np.float32),)
    out = overlapped(planes, jnp.int32(5), jnp.bool_(True), jnp.int32(9))
    np.testing.assert_array_equal(np.asarray(out[0][0]), planes[0])
    assert int(out[1]) == 5 and bool(out[2])


def test_overlapped_loop_verdict_never_deferred_across_dispatches():
    # Exit at round_end with the last super-step converged: the drain must
    # resolve the pending verdict INSIDE the dispatch, so the returned done
    # flag is already true (a stale False would cost the caller one extra
    # dispatch and, worse, desync rounds).
    serial, overlapped, n = _toy_loops(cr=3, target=17)
    # x0=5: after one 3-round super-step every slot is 8 -> verdict fires
    # exactly at round_end=3.
    planes = (np.full(n, 5.0, np.float32),)
    out = overlapped(planes, jnp.int32(0), jnp.bool_(False), jnp.int32(3))
    assert bool(out[2]) and int(out[1]) == 3


# --- end-to-end: schedules are interchangeable -----------------------------


def _grab(final, tag):
    def f(rounds, state):
        final[tag] = state
    return f


def test_chunked_sharded_overlap_on_off_bitwise():
    n = 512
    topo = build_topology("torus3d", n)
    final = {}
    rounds = {}
    for ov in (True, False):
        cfg = SimConfig(n=n, topology="torus3d", algorithm="push-sum",
                        dtype="float32", max_rounds=50_000,
                        overlap_collectives=ov)
        r = run_sharded(topo, cfg, mesh=make_mesh(8),
                        on_chunk=_grab(final, ov))
        rounds[ov] = r.rounds
    assert rounds[True] == rounds[False]
    for f in ("s", "w", "term", "conv"):
        np.testing.assert_array_equal(
            np.asarray(getattr(final[True], f)),
            np.asarray(getattr(final[False], f)),
        )


def test_chunked_sharded_crash_quorum_overlap_on_off():
    # The quorum-termination path under churn: the batched wire must leave
    # the crash-model trajectory and outcome untouched.
    n = 512
    topo = build_topology("torus3d", n)
    res = {}
    for ov in (True, False):
        cfg = SimConfig(n=n, topology="torus3d", algorithm="gossip",
                        crash_schedule="3:40,8:40", quorum=0.85, seed=7,
                        max_rounds=5000, overlap_collectives=ov)
        res[ov] = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert res[True].rounds == res[False].rounds
    assert res[True].outcome == res[False].outcome
    assert res[True].converged_count == res[False].converged_count


def test_fused_pool_sharded_overlap_on_off_bitwise():
    # The batched gather wire through the real composition (the pool
    # kernel runs in tier-1: interpret-mode cost is bounded by the round
    # cap). Includes a crash-schedule leg — the composition's quorum
    # verdict must be schedule-invariant too.
    from cop5615_gossip_protocol_tpu.parallel.fused_pool_sharded import (
        run_fused_pool_sharded,
    )

    n = 131072
    topo = build_topology("full", n)
    final = {}
    for crash in (None, "2:20000"):
        rr = {}
        for ov in (True, False):
            cfg = SimConfig(
                n=n, topology="full", algorithm="gossip", delivery="pool",
                engine="fused", max_rounds=12, n_devices=2,
                crash_schedule=crash, quorum=0.5 if crash else 1.0,
                overlap_collectives=ov,
            )
            rr[ov] = run_fused_pool_sharded(
                topo, cfg, mesh=make_mesh(2),
                on_chunk=_grab(final, (crash, ov)),
            )
        assert rr[True].rounds == rr[False].rounds
        assert rr[True].outcome == rr[False].outcome
        a, b = final[(crash, True)], final[(crash, False)]
        for f in ("count", "active", "conv"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
            )


def test_cli_overlap_flag_round_trips(tmp_path):
    # --overlap-collectives off must reach SimConfig (and produce the same
    # answer — the CLI smoke for the knob).
    from cop5615_gossip_protocol_tpu.cli import main

    out = tmp_path / "rec.jsonl"
    rc = main([
        "512", "torus3d", "gossip", "--platform", "cpu", "--devices", "8",
        "--overlap-collectives", "off", "--quiet", "--jsonl", str(out),
    ])
    assert rc == 0
    import json

    rec = json.loads(out.read_text().splitlines()[-1])
    ref = run(
        build_topology("torus3d", 512),
        SimConfig(n=512, topology="torus3d", algorithm="gossip",
                  n_devices=8),
    )
    assert rec["rounds"] == ref.rounds
