"""Recovery plane (ISSUE 4 tentpole): crash-recovery churn with node
rejoin, threaded config -> ops/faults.revival_plane -> every supporting
engine.

Pinned contracts:

- the death+revival planes are deterministic, tag-disjoint, and identical
  across rebuilds for random (seed, rate, schedule) draws — a seeded sweep
  standing in for a hypothesis property test (hypothesis is not in the
  image);
- crash-recovery runs are bitwise-identical across the chunked, sharded,
  and fused-stencil engines at the same config (gossip: exact trajectories;
  push-sum: rounds + converged set on the stencil path's shared op order);
- gossip revivals rejoin susceptible (count 0) and can re-converge;
- push-sum --rejoin restore conserves mass over live + dead + parked to
  <= 1 ulp at float64 (the PR 1 invariant extended); --rejoin fresh
  deliberately breaks it (the modeled fault);
- checkpoint resume of a crash-recovery run is bitwise, and the stream
  version (v4) gates resumes per the PR 1 sensitivity rules;
- telemetry schema v2's revived_count column reports the rejoin rounds;
- tiers without revival support reject loudly; --revive-* without a crash
  model is a config-time hard error.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import faults, telemetry as telemetry_mod
from cop5615_gossip_protocol_tpu.utils import checkpoint as ckpt


# ---------------------------------------------------------------- config


def test_revive_without_crash_model_is_hard_error():
    with pytest.raises(ValueError, match="nothing to revive"):
        SimConfig(n=64, topology="full", revive_rate=0.1)
    with pytest.raises(ValueError, match="nothing to revive"):
        SimConfig(n=64, topology="full", revive_schedule="5:3")


def test_revive_rate_and_schedule_mutually_exclusive():
    with pytest.raises(ValueError, match="mutually exclusive"):
        SimConfig(n=64, topology="full", crash_rate=0.1,
                  revive_rate=0.1, revive_schedule="5:3")


def test_rejoin_validated():
    with pytest.raises(ValueError, match="rejoin"):
        SimConfig(n=64, topology="full", crash_rate=0.1, revive_rate=0.1,
                  rejoin="bogus")


# ------------------------------------------------- plane properties (sweep)


def test_planes_deterministic_and_tag_disjoint_seeded_sweep():
    # Seeded property sweep over random (seed, rate/schedule) draws: the
    # planes must rebuild identically (every engine derives them from the
    # config alone), revival must strictly follow death, and the three
    # draws (crash, revive, byzantine — ISSUE 16) must be tag-disjoint —
    # distinct tags, and visibly different streams off the same base key.
    assert len({faults.CRASH_TAG, faults.REVIVE_TAG, faults.BYZ_TAG}) == 3
    assert 2**30 <= faults.CRASH_TAG < 2**30 + 2**29
    assert 2**30 <= faults.REVIVE_TAG < 2**30 + 2**29
    assert 2**30 <= faults.BYZ_TAG < 2**30 + 2**29
    from cop5615_gossip_protocol_tpu.models.sweep import REPLICA_TAG0
    assert faults.REVIVE_TAG < REPLICA_TAG0
    assert faults.BYZ_TAG < REPLICA_TAG0

    rng = np.random.default_rng(0)
    for trial in range(8):
        seed = int(rng.integers(0, 2**31 - 1))
        n = int(rng.integers(40, 400))
        if trial % 2 == 0:
            kill = int(rng.integers(1, n // 2))
            rej = int(rng.integers(1, kill + 1))
            cfg = SimConfig(
                n=n, topology="full", seed=seed,
                crash_schedule=f"2:{kill}",
                revive_schedule=f"{int(rng.integers(3, 20))}:{rej}",
                byzantine_schedule=f"{int(rng.integers(1, 30))}:"
                f"{int(rng.integers(1, n // 4))}",
                byzantine_mode="garble",
            )
        else:
            cfg = SimConfig(
                n=n, topology="full", seed=seed,
                crash_rate=float(rng.uniform(0.001, 0.05)),
                revive_rate=float(rng.uniform(0.01, 0.5)),
                byzantine_rate=float(rng.uniform(0.01, 0.2)),
                byzantine_mode="garble",
            )
        a = faults.life_planes(cfg, n)
        abyz = faults.byzantine_plane(cfg, n)
        faults._death_plane_cached.cache_clear()
        faults._revival_plane_cached.cache_clear()
        faults._byzantine_plane_cached.cache_clear()
        b = faults.life_planes(cfg, n)
        np.testing.assert_array_equal(a.death, b.death)
        np.testing.assert_array_equal(a.revive, b.revive)
        np.testing.assert_array_equal(abyz, faults.byzantine_plane(cfg, n))
        # Revival strictly after death; never-dead nodes never revive.
        assert ((a.revive == faults.NEVER) | (a.revive > a.death)).all()
        assert (a.revive[a.death == faults.NEVER] == faults.NEVER).all()
        # Schedule-form adversary counts are exact.
        if cfg.byzantine_schedule:
            rnd_s, ct_s = cfg.byzantine_schedule.split(":")
            assert int((abyz == int(rnd_s)).sum()) == int(ct_s)
            assert int((abyz != faults.NEVER).sum()) == int(ct_s)
        # Tag disjointness as an observable: the uniform draws under the
        # three tags pairwise differ on the same base key.
        key = jax.random.PRNGKey(seed)
        u = {
            tag: np.asarray(jax.random.uniform(
                jax.random.fold_in(key, tag), (n,)))
            for tag in (faults.CRASH_TAG, faults.REVIVE_TAG, faults.BYZ_TAG)
        }
        assert not np.array_equal(u[faults.CRASH_TAG], u[faults.REVIVE_TAG])
        assert not np.array_equal(u[faults.CRASH_TAG], u[faults.BYZ_TAG])
        assert not np.array_equal(u[faults.REVIVE_TAG], u[faults.BYZ_TAG])


def test_revive_schedule_exact_counts_and_overflow():
    cfg = SimConfig(n=200, topology="full", crash_schedule="2:50",
                    revive_schedule="5:20,9:30")
    lp = faults.life_planes(cfg, 200)
    assert int((lp.revive == 5).sum()) == 20
    assert int((lp.revive == 9).sum()) == 30
    # Only dead nodes rejoin.
    assert (lp.death[lp.revive != faults.NEVER] <
            lp.revive[lp.revive != faults.NEVER]).all()
    with pytest.raises(ValueError, match="only .* dead"):
        faults.life_planes(
            SimConfig(n=200, topology="full", crash_schedule="2:10",
                      revive_schedule="5:11"),
            200,
        )


def test_alive_at_dead_window():
    death = np.array([3, faults.NEVER, 0], np.int32)
    revive = np.array([7, faults.NEVER, faults.NEVER], np.int32)
    for r, want in [(2, [1, 1, 0]), (3, [0, 1, 0]), (6, [0, 1, 0]),
                    (7, [1, 1, 0]), (100, [1, 1, 0])]:
        got = np.asarray(faults.alive_at(death, r, revive)).astype(int)
        assert got.tolist() == want, r


# ------------------------------------------- engine parity + rejoin quirks


def _gossip_cfg(**kw):
    kw.setdefault("max_rounds", 4000)
    kw.setdefault("chunk_rounds", 32)
    return SimConfig(n=256, topology="ring", algorithm="gossip",
                     crash_schedule="4:60", revive_schedule="10:60",
                     quorum=0.95, **kw)


def test_gossip_crash_revive_bitwise_chunked_sharded_fused():
    # Acceptance pin: the same crash-recovery config is bitwise-identical
    # across chunked, sharded, and fused-stencil engines. All 60 dead
    # nodes rejoin at round 10, so the healed ring converges fully.
    topo = build_topology("ring", 256)
    results = {
        "chunked": run(topo, _gossip_cfg(engine="chunked")),
        "sharded": run(topo, _gossip_cfg(n_devices=4)),
        "fused": run(topo, _gossip_cfg(engine="fused")),
    }
    ref = results["chunked"]
    assert ref.outcome == "converged"
    for name, r in results.items():
        assert (r.rounds, r.converged_count, r.outcome) == (
            ref.rounds, ref.converged_count, ref.outcome
        ), name


def test_gossip_revivals_rejoin_susceptible_and_reconverge():
    # A revived node restarts at count 0 — so at the revival round the
    # converged count among live nodes DROPS (rejoined nodes are
    # unconverged) and then recovers: they re-converge.
    topo = build_topology("full", 128)
    cfg = SimConfig(n=128, topology="full", algorithm="gossip",
                    crash_schedule="3:40", revive_schedule="30:40",
                    quorum=1.0, max_rounds=4000, chunk_rounds=16,
                    telemetry=True)
    r = run(topo, cfg)
    assert r.outcome == "converged"
    # Quorum 1.0 over live nodes with everyone revived == full population.
    assert r.converged_count == 128
    t = r.telemetry.data
    rev_round = 30  # data[i] is round i's row (start_round 0)
    assert t[rev_round][telemetry_mod.COL_REVIVED] == 40
    assert t[:, telemetry_mod.COL_REVIVED].sum() == 40
    # Live count grows back at the revival round.
    assert t[rev_round][telemetry_mod.COL_LIVE] == 128
    assert t[rev_round - 1][telemetry_mod.COL_LIVE] == 88


def test_pushsum_restore_conserves_mass_to_ulp_float64():
    # The PR 1 invariant extended: with rejoin='restore', total (s, w)
    # mass over live + dead + parked nodes is conserved through death AND
    # rejoin to <= 1 ulp at float64.
    topo = build_topology("full", 200)
    cfg = SimConfig(n=200, topology="full", algorithm="push-sum",
                    dtype="float64", crash_schedule="3:80,7:20",
                    revive_schedule="12:60", quorum=0.9, rejoin="restore",
                    fault_rate=0.2, max_rounds=4000, chunk_rounds=16)
    r = run(topo, cfg)
    assert r.outcome == "converged"
    states = []
    run(topo, cfg, on_chunk=lambda rounds, st: states.append(st))
    total_w = float(jnp.sum(states[-1].w))
    total_s = float(jnp.sum(states[-1].s))
    assert total_w == pytest.approx(200.0, abs=np.spacing(200.0))
    want_s = 200 * 199 / 2.0
    assert total_s == pytest.approx(want_s, abs=4 * np.spacing(want_s))


def test_pushsum_fresh_discards_parked_mass():
    # rejoin='fresh' is the non-conserving fault: revived nodes restart at
    # (s=x_i, w=0), so total weight mass DROPS by the parked weight.
    topo = build_topology("full", 200)
    cfg = SimConfig(n=200, topology="full", algorithm="push-sum",
                    dtype="float64", crash_schedule="3:80",
                    revive_schedule="12:80", quorum=1.0, rejoin="fresh",
                    max_rounds=4000, chunk_rounds=16)
    states = []
    r = run(topo, cfg, on_chunk=lambda rounds, st: states.append(st))
    assert r.outcome == "converged"
    total_w = float(jnp.sum(states[-1].w))
    assert total_w < 200.0 - 1e-6  # parked weight was discarded at rejoin


def test_pushsum_revive_parity_chunked_vs_sharded_and_fused():
    base = dict(n=256, topology="ring", algorithm="push-sum",
                crash_schedule="4:50", revive_rate=0.08, quorum=0.85,
                max_rounds=6000, chunk_rounds=32)
    topo = build_topology("ring", 256)
    for rejoin in ("restore", "fresh"):
        rc = run(topo, SimConfig(**base, rejoin=rejoin, engine="chunked"))
        rf = run(topo, SimConfig(**base, rejoin=rejoin, engine="fused"))
        rs = run(topo, SimConfig(**base, rejoin=rejoin, n_devices=4))
        assert rc.rounds == rf.rounds == rs.rounds, rejoin
        assert rc.converged_count == rf.converged_count == rs.converged_count


def test_pool_delivery_revive_parity():
    base = dict(n=1000, topology="full", algorithm="gossip",
                delivery="pool", crash_schedule="3:200",
                revive_schedule="8:100", quorum=0.9, max_rounds=500,
                chunk_rounds=16)
    topo = build_topology("full", 1000)
    rc = run(topo, SimConfig(**base, engine="chunked"))
    rs = run(topo, SimConfig(**base, engine="chunked", n_devices=4))
    assert rc.outcome == "converged"
    assert (rc.rounds, rc.converged_count) == (rs.rounds, rs.converged_count)


@pytest.mark.slow  # interpret-mode pool kernel run; tier-1 budget note in test_fused.py
def test_fused_pool_revive_parity_bitwise():
    base = dict(n=1000, topology="full", algorithm="gossip",
                delivery="pool", crash_schedule="3:200",
                revive_schedule="8:100", quorum=0.9, max_rounds=500,
                chunk_rounds=16)
    topo = build_topology("full", 1000)
    rc = run(topo, SimConfig(**base, engine="chunked"))
    rf = run(topo, SimConfig(**base, engine="fused"))
    assert (rc.rounds, rc.converged_count) == (rf.rounds, rf.converged_count)


# ------------------------------------------------------ checkpoint/resume


def test_checkpoint_resume_revive_run_bitwise(tmp_path):
    topo = build_topology("full", 200)
    cfg = SimConfig(n=200, topology="full", algorithm="push-sum",
                    crash_schedule="3:80", revive_schedule="20:60",
                    quorum=0.9, rejoin="restore", max_rounds=4000,
                    chunk_rounds=8)
    snaps = []
    ref = run(topo, cfg, on_chunk=lambda rounds, st: snaps.append((rounds, st)))
    assert ref.outcome == "converged"
    # Resume from a boundary BEFORE the revival round: the rejoin reset
    # runs inside the revival round's body, so the resumed trajectory
    # replays it identically.
    rounds0, st0 = snaps[1]
    assert rounds0 < 20
    path = tmp_path / "ck.npz"
    ckpt.save(path, st0, rounds0, cfg)
    st, rnds, cfg2 = ckpt.load(path)
    resumed = run(topo, cfg2, start_state=st, start_round=rnds)
    assert resumed.rounds == ref.rounds
    assert resumed.converged_count == ref.converged_count
    assert resumed.estimate_mae == ref.estimate_mae


def test_checkpoint_stream_v4_sensitivity(tmp_path):
    # A revive config refuses checkpoints written before stream v4 (their
    # revival derivation is unknowable); a crash-stop config from v3 still
    # loads — only configs that consume a changed stream are refused.
    from cop5615_gossip_protocol_tpu.models import pushsum as ps
    cfg = SimConfig(n=64, topology="full", algorithm="push-sum",
                    crash_rate=0.01, revive_rate=0.1)
    st = ps.init_state(64, jnp.float32, 0)
    path = tmp_path / "old.npz"
    ckpt.save(path, st, 8, cfg)
    # Rewrite the archive with a v3 stream marker.
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    arrays["__stream__"] = np.asarray(3)
    np.savez_compressed(path, **arrays)
    ckpt._refresh_digests(path)  # rewrite in place: re-bless the digests
    with pytest.raises(ValueError, match="stream"):
        ckpt.load(path)
    # Same vintage marker, no revive model: loads fine.
    cfg_stop = dataclasses.replace(cfg, revive_rate=0.0)
    path2 = tmp_path / "old_stop.npz"
    ckpt.save(path2, st, 8, cfg_stop)
    with np.load(path2) as z:
        arrays = {k: z[k] for k in z.files}
    arrays["__stream__"] = np.asarray(3)
    np.savez_compressed(path2, **arrays)
    ckpt._refresh_digests(path2)
    _, rnds, _ = ckpt.load(path2)
    assert rnds == 8


def test_checkpoint_stream_v5_sensitivity(tmp_path):
    # ISSUE 16, the same per-version rule one notch up: v4 -> v5 only
    # ADDED the byzantine adversary-plane stream, so a byzantine config
    # refuses any pre-v5 archive while a v4 checkpoint without a
    # byzantine model still loads under v5.
    from cop5615_gossip_protocol_tpu.models import pushsum as ps
    cfg = SimConfig(n=64, topology="full", algorithm="push-sum",
                    byzantine_rate=0.05, byzantine_mode="mass_inflate")
    st = ps.init_state(64, jnp.float32, 0)
    path = tmp_path / "old_byz.npz"
    ckpt.save(path, st, 8, cfg)
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    arrays["__stream__"] = np.asarray(4)
    np.savez_compressed(path, **arrays)
    ckpt._refresh_digests(path)
    with pytest.raises(ValueError, match="stream"):
        ckpt.load(path)
    # Same v4 marker, no byzantine model: loads fine (the added stream is
    # never consumed).
    cfg_honest = dataclasses.replace(cfg, byzantine_rate=0.0)
    path2 = tmp_path / "old_honest.npz"
    ckpt.save(path2, st, 8, cfg_honest)
    with np.load(path2) as z:
        arrays = {k: z[k] for k in z.files}
    arrays["__stream__"] = np.asarray(4)
    np.savez_compressed(path2, **arrays)
    ckpt._refresh_digests(path2)
    _, rnds, _ = ckpt.load(path2)
    assert rnds == 8


# ------------------------------------------------------- tier rejections


def test_unsupported_tiers_reject_revive_loudly():
    cfg_kw = dict(algorithm="gossip", crash_rate=0.01, revive_rate=0.1,
                  quorum=0.9)

    # Streaming pool tier (pool2).
    from cop5615_gossip_protocol_tpu.ops import fused_pool2
    topo = build_topology("full", 4096)
    reason = fused_pool2.pool2_support(
        topo, SimConfig(n=4096, topology="full", delivery="pool", **cfg_kw)
    )
    assert reason is not None and "revive" in reason

    # Sharded fused pool composition.
    from cop5615_gossip_protocol_tpu.parallel.fused_pool_sharded import (
        plan_fused_pool_sharded,
    )
    plan = plan_fused_pool_sharded(
        topo, SimConfig(n=4096, topology="full", delivery="pool",
                        n_devices=2, engine="fused", **cfg_kw), 2
    )
    assert isinstance(plan, str) and "revive" in plan

    # Lattice compositions reject the whole failure model already.
    from cop5615_gossip_protocol_tpu.parallel.fused_sharded import (
        plan_fused_sharded,
    )
    topo_r = build_topology("ring", 65536)
    plan = plan_fused_sharded(
        topo_r, SimConfig(n=65536, topology="ring", n_devices=2,
                          engine="fused", **cfg_kw), 2
    )
    assert isinstance(plan, str)

    # engine='fused' on an ineligible tier raises through run().
    with pytest.raises(ValueError, match="revive|failure"):
        run(
            build_topology("full", 4096),
            SimConfig(n=4096, topology="full", delivery="pool",
                      engine="fused", n_devices=2, **cfg_kw),
        )


def test_replica_sweep_shares_config_pure_planes():
    # The vmapped sweep reuses make_round_fn + _done_predicate, so the
    # revival plane (config-pure) serves every replica; replica 0 stays
    # bitwise the unbatched run under churn + recovery.
    from cop5615_gossip_protocol_tpu.models.sweep import run_replicas
    topo = build_topology("full", 128)
    cfg = SimConfig(n=128, topology="full", algorithm="gossip",
                    crash_schedule="3:40", revive_schedule="9:40",
                    quorum=0.95, max_rounds=2000, chunk_rounds=16)
    sweep = run_replicas(topo, cfg, 3)
    solo = run(topo, cfg)
    assert sweep.rounds[0] == solo.rounds
    assert sweep.converged[0] == solo.converged


# ------------------------------------- durable state plane (ISSUE 19)
#
# Checkpoint integrity (per-array SHA-256 + data/config digests in the
# sidecar), generation retention, load_latest_intact quarantine-and-fall-
# back, the kill-at-every-fault-point property, the chunk-boundary
# checkpoint-failure policy, and elastic mesh-shrink/grow resume.


class SimulatedCrash(BaseException):
    """A kill injected at a checkpoint fault point. BaseException on
    purpose: the engines' graceful-degradation ladder catches Exception
    rungs, and a simulated process death must end the save exactly where
    it fired rather than being retried or degraded around."""


def _assert_states_bitwise(got, want, label=""):
    for f in want._fields:
        a = np.asarray(getattr(got, f))
        b = np.asarray(getattr(want, f))
        assert np.array_equal(a, b), (label, f)


# ----------------------------------------------- integrity + quarantine


def _pushsum_checkpoint(tmp_path, rounds=8, **save_kw):
    cfg = SimConfig(n=64, topology="full", algorithm="push-sum",
                    max_rounds=500, chunk_rounds=8)
    topo = build_topology("full", 64)
    snaps = []
    run(topo, cfg, on_chunk=lambda r, s: snaps.append((r, s)))
    path = tmp_path / "ck.npz"
    r0, st0 = snaps[0]
    ckpt.save(path, st0, rounds, cfg, **save_kw)
    return path, cfg, st0


def test_checkpoint_mispair_window_refused(tmp_path):
    # The ISSUE 19 bugfix pin. Before this PR save() renamed the sidecar
    # BEFORE the data archive, so a kill between the two renames left a
    # NEW sidecar paired with the OLD archive — and load() used the stale
    # state silently. Construct that exact window: two saves to the same
    # plain path, then put the first save's archive back under the second
    # save's sidecar.
    path, cfg, st0 = _pushsum_checkpoint(tmp_path, rounds=8)
    old_archive = path.read_bytes()
    ckpt.save(path, st0, 16, cfg)
    path.write_bytes(old_archive)  # the historical torn-rename window
    with pytest.raises(ckpt.CheckpointIntegrityError, match="mispaired"):
        ckpt.load(path)


def test_checkpoint_new_rename_order_window_refused(tmp_path):
    # The window the NEW rename order (data first) can leave behind: a
    # kill after the archive rename but before the sidecar rename pairs
    # the new archive with the OLD sidecar. Also refused — the sidecar's
    # data_sha256 no longer matches.
    path, cfg, st0 = _pushsum_checkpoint(tmp_path, rounds=8)

    def kill(point, _path):
        if point == "after-data-rename":
            raise SimulatedCrash(point)

    ckpt.FAULT_HOOK = kill
    try:
        with pytest.raises(SimulatedCrash):
            ckpt.save(path, st0, 16, cfg)
    finally:
        ckpt.FAULT_HOOK = None
    with pytest.raises(ckpt.CheckpointIntegrityError, match="mispaired"):
        ckpt.load(path)


def test_checkpoint_bitflip_names_corrupt_array(tmp_path):
    # A valid zip whose content silently changed (bit rot after the
    # digests were recorded): the refusal names the corrupt array — a
    # structured verdict, never a numpy traceback.
    path, cfg, st0 = _pushsum_checkpoint(tmp_path, rounds=8)
    with np.load(path, allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files}
    victim = next(k for k in arrays if not k.startswith("__"))
    flipped = arrays[victim].copy()
    flipped.reshape(-1).view(np.uint8)[0] ^= 0x40
    arrays[victim] = flipped
    np.savez_compressed(path, **arrays)  # digests deliberately NOT refreshed
    with pytest.raises(ckpt.CheckpointIntegrityError) as ei:
        ckpt.load(path)
    assert victim in ei.value.corrupt_arrays
    assert [k for k in ei.value.corrupt_arrays if k != victim] == []


def test_checkpoint_corrupt_sidecar_refused(tmp_path):
    path, cfg, st0 = _pushsum_checkpoint(tmp_path, rounds=8)
    sidecar = path.with_suffix(path.suffix + ".json")
    sidecar.write_text(sidecar.read_text()[:-20])  # torn sidecar write
    with pytest.raises(ckpt.CheckpointIntegrityError, match="sidecar"):
        ckpt.load(path)


def test_load_latest_intact_quarantines_and_falls_back(tmp_path):
    # Two generations, newest truncated mid-archive: load_latest_intact
    # renames the broken pair to *.corrupt, emits one structured
    # quarantine event, and returns the older intact generation.
    cfg = SimConfig(n=64, topology="full", algorithm="push-sum",
                    max_rounds=500, chunk_rounds=8)
    topo = build_topology("full", 64)
    snaps = []
    run(topo, cfg, on_chunk=lambda r, s: snaps.append((r, s)))
    path = tmp_path / "ck.npz"
    ckpt.save(path, snaps[0][1], snaps[0][0], cfg, keep=3)
    ckpt.save(path, snaps[1][1], snaps[1][0], cfg, keep=3)
    gens = ckpt.candidate_paths(path)
    newest = gens[0]
    newest.write_bytes(newest.read_bytes()[:200])  # torn write

    events = []
    hit = ckpt.load_latest_intact(path, on_event=lambda **f: events.append(f))
    assert hit is not None
    st, rnds, cfg2, info = hit
    assert rnds == snaps[0][0]
    assert info["generation"] == 0
    _assert_states_bitwise(st, snaps[0][1], label="fallback-state")

    [ev] = events
    assert set(ev) >= {"path", "reason", "corrupt_arrays", "quarantined"}
    assert "unreadable" in ev["reason"]
    assert all(p.endswith(".corrupt") for p in ev["quarantined"])
    assert newest not in ckpt.candidate_paths(path)
    assert list(tmp_path.glob("*.corrupt"))


def test_load_latest_intact_none_when_nothing_intact(tmp_path):
    path, cfg, st0 = _pushsum_checkpoint(tmp_path, rounds=8)
    path.write_bytes(path.read_bytes()[:100])
    events = []
    assert ckpt.load_latest_intact(
        path, on_event=lambda **f: events.append(f)) is None
    assert len(events) == 1


def test_checkpoint_generation_retention(tmp_path):
    # keep=K prunes beyond K generations; the manifest and the plain-path
    # link always track the newest; generation indices are monotonic.
    import json

    path, cfg, st0 = _pushsum_checkpoint(tmp_path, rounds=8, keep=2)
    for rounds in (16, 24, 32):
        info = ckpt.save(path, st0, rounds, cfg, keep=2)
    assert info["generation"] == 3  # zero-indexed, monotonic
    gens = ckpt.candidate_paths(path)
    assert len(gens) == 2  # pruned to keep=2 (plain path is a symlink)
    manifest = json.loads((tmp_path / "ck.manifest.json").read_text())
    assert sorted(e["generation"] for e in manifest["generations"]) == [2, 3]
    assert {e["generation"]: e["rounds"] for e in manifest["generations"]}[3] == 32
    assert path.is_symlink()
    st, rnds, cfg2 = ckpt.load(path)
    assert rnds == 32


# ------------------------------------------- kill-at-every-fault-point


_DURABLE_CFGS = {
    "gossip-crash-revive": dict(
        n=256, topology="full", algorithm="gossip",
        crash_schedule="3:40", revive_schedule="8:40", quorum=0.95,
        max_rounds=2000, chunk_rounds=8, n_devices=2),
    "push-sum": dict(
        n=256, topology="full", algorithm="push-sum",
        max_rounds=2000, chunk_rounds=8, n_devices=2),
}


@pytest.fixture(scope="module", params=sorted(_DURABLE_CFGS))
def durable_control(request):
    """One uninterrupted control per config: (name, cfg, topo, result,
    boundary snapshots). Module-scoped — the sweep below replays resumes
    against it at every fault point without re-running the control."""
    name = request.param
    cfg = SimConfig(**_DURABLE_CFGS[name])
    topo = build_topology(cfg.topology, cfg.n)
    snaps = []
    res = run(topo, cfg, on_chunk=lambda r, s: snaps.append((r, s)))
    assert res.outcome == "converged"
    assert len(snaps) >= 3
    return name, cfg, topo, res, snaps


@pytest.mark.parametrize("point", ckpt.FAULT_POINTS)
def test_kill_at_every_fault_point_recovers_bitwise(
        durable_control, point, tmp_path):
    # THE durability property: a kill at ANY fault point of a checkpoint
    # write leaves the store recoverable — load_latest_intact returns an
    # intact generation (quarantining any broken pair with a structured
    # event, never a traceback) and the resumed run finishes bitwise-
    # equal to the uninterrupted control.
    name, cfg, topo, control, snaps = durable_control
    path = tmp_path / "ck.npz"
    r0, st0 = snaps[0]
    r1, st1 = snaps[1]
    ckpt.save(path, st0, r0, cfg, keep=3)  # one known-intact generation

    def kill(p, _path):
        if p == point:
            raise SimulatedCrash(p)

    ckpt.FAULT_HOOK = kill
    try:
        with pytest.raises(SimulatedCrash):
            ckpt.save(path, st1, r1, cfg, keep=3)
    finally:
        ckpt.FAULT_HOOK = None

    events = []
    hit = ckpt.load_latest_intact(path, on_event=lambda **f: events.append(f))
    assert hit is not None, (name, point)
    st, rnds, cfg2, info = hit
    assert rnds in (r0, r1), (name, point)
    for ev in events:
        assert set(ev) >= {"path", "reason", "corrupt_arrays", "quarantined"}

    resumed_snaps = []
    resumed = run(topo, cfg2, start_state=st, start_round=rnds,
                  on_chunk=lambda r, s: resumed_snaps.append((r, s)))
    assert (resumed.rounds, resumed.converged_count, resumed.outcome) == (
        control.rounds, control.converged_count, control.outcome), (name, point)
    want = dict(snaps)
    fr, fs = resumed_snaps[-1]
    _assert_states_bitwise(fs, want[fr], label=(name, point))


# ------------------------------- chunk-boundary checkpoint I/O failure


def test_checkpoint_hook_failure_continues_by_default():
    # models/pipeline.run_chunks hook_error policy: an OSError from the
    # chunk-boundary hook (a failed checkpoint write) loses one interval,
    # records the failure on RunResult.hook_failures, and the run's
    # result is untouched.
    import errno

    cfg = SimConfig(**_DURABLE_CFGS["push-sum"])
    topo = build_topology(cfg.topology, cfg.n)
    control = run(topo, cfg)

    calls = []

    def flaky_hook(rounds, st):
        calls.append(rounds)
        if len(calls) == 2:
            raise OSError(errno.ENOSPC, "No space left on device")

    res = run(topo, cfg, on_chunk=flaky_hook)
    assert (res.rounds, res.converged_count, res.outcome) == (
        control.rounds, control.converged_count, control.outcome)
    [fail] = res.hook_failures
    assert fail["rounds"] == calls[1]
    assert "OSError" in fail["error"]
    assert control.hook_failures is None  # clean runs don't carry the field


def test_strict_checkpoint_restores_fail_fast():
    import errno

    cfg = dataclasses.replace(
        SimConfig(**_DURABLE_CFGS["push-sum"]), strict_checkpoint=True)
    topo = build_topology(cfg.topology, cfg.n)

    def flaky_hook(rounds, st):
        raise OSError(errno.ENOSPC, "No space left on device")

    with pytest.raises(OSError):
        run(topo, cfg, on_chunk=flaky_hook)


def test_env_fault_enospc_spec(tmp_path, monkeypatch):
    # The GOSSIP_TPU_CKPT_FAULT chaos gate: enospc:<nth>[:<count>] makes
    # the nth save (zero-indexed) raise ENOSPC — the same failure the
    # policy test above injects, but reachable from a subprocess without
    # touching code.
    monkeypatch.setenv(ckpt.FAULT_ENV, "enospc:1:1")
    ckpt._ENV_STATE["saves"] = 0
    ckpt._ENV_STATE["enospc_left"] = None
    path, cfg, st0 = _pushsum_checkpoint(tmp_path, rounds=8)  # save 0: ok
    with pytest.raises(OSError) as ei:
        ckpt.save(path, st0, 16, cfg)  # save 1: ENOSPC
    assert ei.value.errno == __import__("errno").ENOSPC
    ckpt.save(path, st0, 24, cfg)  # save 2: budget spent, ok again
    st, rnds, _ = ckpt.load(path)
    assert rnds == 24


# ------------------------------------- elastic mesh-shrink/grow resume


_ELASTIC_CASES = [
    # (label, extra config, P -> P'). Gossip state is integer so the cut
    # moves across the single-device boundary bitwise; push-sum float32
    # state is pinned within the sharded family (the single-device chunked
    # engine preserves denormals the sharded all-reduce flushes to zero,
    # so P'=1 for push-sum is numerically-close, not bitwise — see README
    # Durability).
    ("scatter-gossip-shrink-to-1",
     dict(algorithm="gossip", crash_schedule="3:40", revive_schedule="8:40",
          quorum=0.95), 2, 1),
    ("scatter-gossip-grow",
     dict(algorithm="gossip", crash_schedule="3:40", revive_schedule="8:40",
          quorum=0.95), 2, 4),
    ("scatter-pushsum-shrink", dict(algorithm="push-sum"), 4, 2),
    ("scatter-pushsum-grow", dict(algorithm="push-sum"), 2, 4),
    ("pool-gossip-shrink", dict(algorithm="gossip", delivery="pool"), 4, 2),
    ("pool-pushsum-grow",
     dict(algorithm="push-sum", delivery="pool"), 2, 4),
]


@pytest.mark.parametrize("label,kw,p_from,p_to",
                         _ELASTIC_CASES, ids=[c[0] for c in _ELASTIC_CASES])
def test_elastic_mesh_resume_bitwise(label, kw, p_from, p_to, tmp_path):
    # A checkpoint cut at P devices resumes at P' devices (shrink, grow,
    # and down to a single device) bitwise-equal to an uninterrupted run
    # at P': checkpoints are stored in global row order and re-placed
    # through parallel/mesh.put_rows / put_global at load, so the on-disk
    # format owes nothing to the mesh that wrote it.
    cfg_from = SimConfig(n=256, topology="full", max_rounds=2000,
                         chunk_rounds=8, n_devices=p_from, **kw)
    topo = build_topology("full", 256)

    snaps = []
    src = run(topo, cfg_from, on_chunk=lambda r, s: snaps.append((r, s)))
    assert src.outcome == "converged"
    r0, st0 = snaps[1]
    path = tmp_path / "ck.npz"
    ckpt.save(path, st0, r0, cfg_from)
    st, rnds, saved_cfg = ckpt.load(path)

    cfg_to = dataclasses.replace(saved_cfg, n_devices=p_to)
    ctl_snaps = []
    control = run(topo, cfg_to, on_chunk=lambda r, s: ctl_snaps.append((r, s)))

    res_snaps = []
    resumed = run(topo, cfg_to, start_state=st, start_round=rnds,
                  on_chunk=lambda r, s: res_snaps.append((r, s)))
    assert (resumed.rounds, resumed.converged_count, resumed.outcome) == (
        control.rounds, control.converged_count, control.outcome), label
    want = dict(ctl_snaps)
    assert res_snaps and all(r in want for r, _ in res_snaps)
    for r, s in res_snaps:
        _assert_states_bitwise(s, want[r], label=(label, r))
