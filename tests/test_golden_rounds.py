"""Absolute golden round counts (SURVEY §4; VERDICT r3 #7).

Every other equivalence test in this suite pins engines against EACH OTHER
— all of them share one sampling stream (ops/sampling.py + the in-kernel
threefry twins), so a semantic drift there would move every engine in
lockstep and no relative test would notice. This file is the absolute
oracle: rounds-to-converge and converged counts for fixed
(topology, algorithm, n, delivery, seed), generated ONCE on the chunked
CPU path (float32, default deltas) and checked in as
tests/golden_rounds.json.

If this test fails after an intentional sampling/semantics change,
regenerate the table with the snippet in the JSON's sibling docstring
below and say so in the commit message — silently regenerating defeats
the oracle.

Regeneration:
    python - <<'EOF'
    import json, jax
    jax.config.update('jax_platforms', 'cpu')
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.models.runner import run
    rows = json.load(open('tests/golden_rounds.json'))
    for row in rows:
        cfg = SimConfig(n=row['n'], topology=row['topology'],
                        algorithm=row['algorithm'], delivery=row['delivery'],
                        seed=row['seed'], engine='chunked', max_rounds=200000)
        r = run(build_topology(row['topology'], row['n'], seed=row['seed']), cfg)
        row.update(rounds=r.rounds, converged_count=r.converged_count,
                   converged=r.converged)
    json.dump(rows, open('tests/golden_rounds.json', 'w'), indent=1)
    EOF
"""

import json
from pathlib import Path

import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run

GOLDEN = json.loads(
    (Path(__file__).parent / "golden_rounds.json").read_text()
)


@pytest.mark.parametrize(
    "row", GOLDEN,
    ids=[
        f"{r['topology']}-{r['algorithm']}-{r['n']}-{r['delivery']}-s{r['seed']}"
        for r in GOLDEN
    ],
)
def test_golden_rounds(row):
    cfg = SimConfig(
        n=row["n"], topology=row["topology"], algorithm=row["algorithm"],
        delivery=row["delivery"], seed=row["seed"], engine="chunked",
        max_rounds=200000,
    )
    topo = build_topology(row["topology"], row["n"], seed=row["seed"])
    r = run(topo, cfg)
    if row["algorithm"] == "push-sum" and row["delivery"] == "scatter":
        # Scatter-add accumulation order is implementation-defined
        # (ops/delivery.deliver docstring) and differs ACROSS XLA RELEASES;
        # at float32 the ulp drift, amplified by the term-counter reset,
        # shifts round counts by tens of percent — the same contract the
        # sharded psum_scatter path accepts (parallel/sharded.py module
        # docstring). These rows pin the convergence envelope, not the
        # round count; every order-deterministic row below stays exact.
        assert abs(r.rounds - row["rounds"]) <= row["rounds"] // 2, (
            f"round count {r.rounds} left the golden envelope "
            f"[{row['rounds'] // 2}, {row['rounds'] * 3 // 2}]"
        )
    else:
        assert r.rounds == row["rounds"], (
            f"absolute round count drifted: {r.rounds} != golden "
            f"{row['rounds']} — the shared sampling stream or round "
            "semantics changed (see module docstring before regenerating)"
        )
    assert r.converged_count == row["converged_count"]
    assert r.converged == row["converged"]
