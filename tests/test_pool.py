"""Offset-pool sampling/delivery for the implicit full topology
(ops/sampling.pool_offsets, ops/delivery.deliver_pool).

Oracles:

- delivery equivalence: the masked-roll inbox must equal a scatter-add over
  the implied targets (exact for int channels, float-order tolerance for f32);
- receiver-side suppression must equal sender-side suppression exactly
  (models/gossip.py docstring argument, pinned per-round here);
- mass conservation per round;
- convergence quality: pool sampling must converge in a comparable number of
  rounds to iid scatter sampling (the pool's correlated draws still form an
  expander per round), with the same estimate quality;
- the sharded scatter fallback must follow the same targets as the
  single-device roll path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import delivery, sampling


def _pool_parts(seed, rnd, n, K):
    key = jax.random.PRNGKey(seed)
    kr = sampling.round_key(key, rnd)
    bits = sampling.uniform_bits(kr, n)
    offs = sampling.pool_offsets(kr, K, n)
    choice = sampling.pool_choice(bits, K)
    return choice, offs


def test_pool_offsets_range_and_choice_uniformity():
    n, K = 1000, 8
    choice, offs = _pool_parts(0, 3, n, K)
    offs = np.asarray(offs)
    assert ((offs >= 1) & (offs < n)).all()
    counts = np.bincount(np.asarray(choice), minlength=K)
    # 1000 draws over 8 slots: each slot expected 125, sd ~10.5.
    assert counts.min() > 60 and counts.max() < 200


@pytest.mark.parametrize("n,K", [(256, 8), (1000, 16), (37, 4)])
def test_deliver_pool_matches_scatter(n, K):
    choice, offs = _pool_parts(1, 5, n, K)
    ids = jnp.arange(n, dtype=jnp.int32)
    targets = sampling.targets_pool(choice, offs, ids, n)
    vals_i = jnp.arange(n, dtype=jnp.int32) % 7 + 1
    vals_f = jnp.linspace(0.5, 2.0, n, dtype=jnp.float32)
    inbox = delivery.deliver_pool(jnp.stack([vals_i.astype(jnp.float32), vals_f]),
                                  choice, offs)
    want_i = delivery.deliver(vals_i, targets, n)
    want_f = delivery.deliver(vals_f, targets, n)
    assert (np.asarray(inbox[0]).astype(np.int64) == np.asarray(want_i)).all()
    np.testing.assert_allclose(np.asarray(inbox[1]), np.asarray(want_f), rtol=1e-6)


def test_receiver_side_suppression_matches_sender_side():
    # The equivalence the whole codebase rides on (models/gossip.py): zeroing
    # a converged receiver's inbox == every sender probing the same
    # round-start conv vector and not sending. Pinned per-round on random
    # states: both forms must produce the same next state, element-wise.
    from cop5615_gossip_protocol_tpu.models import gossip as gossip_mod

    n, K = 300, 8
    rumor_target = 5
    for seed in range(5):
        k0, k1, k2 = jax.random.split(jax.random.PRNGKey(seed), 3)
        choice, offs = _pool_parts(seed, 9, n, K)
        ids = jnp.arange(n, dtype=jnp.int32)
        targets = sampling.targets_pool(choice, offs, ids, n)
        count = jax.random.randint(k0, (n,), 0, rumor_target + 2)
        conv = count >= rumor_target
        active = conv | jax.random.bernoulli(k1, 0.5, (n,))
        state = gossip_mod.GossipState(count=count, active=active, conv=conv)
        send_ok = jax.random.bernoulli(k2, 0.9, (n,))
        # sender-side reference implementation
        vals_sup = (active & send_ok & ~conv[targets]).astype(jnp.int32)
        want = gossip_mod.absorb(
            state, delivery.deliver(vals_sup, targets, n), rumor_target
        )
        # receiver-side (the shipped path)
        vals = gossip_mod.send_values(state, send_ok)
        got = gossip_mod.absorb(
            state, delivery.deliver(vals, targets, n), rumor_target,
            suppress=True,
        )
        for f in state._fields:
            assert (np.asarray(getattr(got, f)) == np.asarray(getattr(want, f))).all(), f


def test_pool_mass_conservation():
    n, K = 512, 8
    choice, offs = _pool_parts(4, 0, n, K)
    s = jnp.arange(n, dtype=jnp.float32)
    w = jnp.ones((n,), jnp.float32)
    inbox = delivery.deliver_pool(jnp.stack([s * 0.5, w * 0.5]), choice, offs)
    s_new = s * 0.5 + inbox[0]
    w_new = w * 0.5 + inbox[1]
    np.testing.assert_allclose(float(jnp.sum(s_new)), float(jnp.sum(s)), rtol=1e-6)
    np.testing.assert_allclose(float(jnp.sum(w_new)), float(jnp.sum(w)), rtol=1e-6)


@pytest.mark.parametrize("pool_size", [4, 8, 16])
def test_pool_pushsum_convergence_comparable_to_scatter(pool_size):
    # The headline-semantics check: offset-pool sampling must not degrade
    # convergence. Rounds within 1.6x of iid scatter sampling; estimates good.
    n = 4096
    base = dict(n=n, topology="full", algorithm="push-sum", max_rounds=5000)
    r_scatter = run(build_topology("full", n),
                    SimConfig(delivery="scatter", **base))
    r_pool = run(build_topology("full", n),
                 SimConfig(delivery="pool", pool_size=pool_size, **base))
    assert r_scatter.converged and r_pool.converged
    assert r_pool.rounds <= int(r_scatter.rounds * 1.6) + 5
    assert r_pool.estimate_mae < 1e-2
    assert r_pool.converged_count == n


def test_pool_gossip_converges():
    n = 2048
    cfg = SimConfig(n=n, topology="full", algorithm="gossip",
                    delivery="pool", max_rounds=5000)
    r = run(build_topology("full", n), cfg)
    assert r.converged and r.converged_count == n


def test_pool_gossip_reference_suppression():
    # Reference semantics on full: Q1 population n+1, Q2 11th receipt,
    # suppression applied receiver-side (models/gossip.absorb).
    n = 512
    cfg = SimConfig(n=n, topology="full", algorithm="gossip",
                    semantics="reference", delivery="pool", max_rounds=8000)
    r = run(build_topology("full", n, semantics="reference"), cfg)
    assert r.converged and r.converged_count >= r.target_count


def test_pool_sharded_matches_single_device():
    # Mesh-divisible population: the sharded run delivers by dynamic global
    # rolls (parallel/halo.global_roll_dynamic — same masked-roll order as
    # the single-device path); gossip integer trajectories must agree
    # exactly. The deeper bitwise pins live in tests/test_halo.py.
    n = 1024  # divisible by 8 devices: identical RNG slicing
    base = dict(n=n, topology="full", algorithm="gossip",
                delivery="pool", max_rounds=5000)
    r1 = run(build_topology("full", n), SimConfig(**base))
    r8 = run(build_topology("full", n), SimConfig(n_devices=8, **base))
    assert r1.rounds == r8.rounds
    assert r1.converged_count == r8.converged_count


def test_pool_sharded_nondivisible_falls_back_to_scatter():
    # n % n_devices != 0: pad slots inside the ring would corrupt a global
    # roll, so the sharded pool path falls back to scatter + psum_scatter
    # over targets_pool — same sampled targets, so gossip trajectories still
    # match the single-device roll path exactly.
    n = 1001
    base = dict(n=n, topology="full", algorithm="gossip",
                delivery="pool", max_rounds=5000)
    r1 = run(build_topology("full", n), SimConfig(**base))
    r8 = run(build_topology("full", n), SimConfig(n_devices=8, **base))
    assert r8.converged
    assert r1.rounds == r8.rounds
    assert r1.converged_count == r8.converged_count


def test_pool_config_validation():
    with pytest.raises(ValueError, match="pool"):
        SimConfig(n=100, topology="line", delivery="pool")
    with pytest.raises(ValueError, match="power of two"):
        SimConfig(n=100, topology="full", delivery="pool", pool_size=6)
    with pytest.raises(ValueError, match="full"):
        run(build_topology("line", 64),
            SimConfig(n=64, topology="full", delivery="pool"))


def test_pool_fault_injection_conserves_mass():
    n = 1024
    cfg = SimConfig(n=n, topology="full", algorithm="push-sum",
                    delivery="pool", fault_rate=0.3, max_rounds=8000)
    r = run(build_topology("full", n), cfg)
    assert r.converged
    assert r.estimate_mae < 1e-2


def test_pool_combined_drop_crash_conserves_mass():
    # Drop gate + crash-stop churn together (ops/faults.py): dropped
    # senders keep their full mass, dead nodes park delivered mass — the
    # total over live + dead nodes never moves. float64 makes the halving
    # and scatter-adds tight enough to pin <= 1 ulp of the initial totals.
    import numpy as np

    n = 1024
    cfg = SimConfig(n=n, topology="full", algorithm="push-sum",
                    delivery="pool", fault_rate=0.3, crash_schedule="4:200",
                    quorum=0.9, max_rounds=8000, dtype="float64")
    cap = {}
    r = run(build_topology("full", n), cfg,
            on_chunk=lambda rounds, st: cap.update(state=st))
    assert r.converged and r.outcome == "converged"
    st = cap["state"]
    s0, w0 = n * (n - 1) / 2.0, float(n)
    assert abs(np.asarray(st.s, np.float64).sum() - s0) <= np.spacing(s0)
    assert abs(np.asarray(st.w, np.float64).sum() - w0) <= np.spacing(w0)


def test_pool_rejected_for_reference_pushsum():
    cfg = SimConfig(n=64, topology="full", algorithm="push-sum",
                    semantics="reference", delivery="pool")
    with pytest.raises(ValueError, match="single-walk"):
        run(build_topology("full", 64, semantics="reference"), cfg)
