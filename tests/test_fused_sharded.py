"""Fused x sharded composition (parallel/fused_sharded.py), interpret mode
on the 8-virtual-CPU-device mesh.

Contracts:
- chunk_rounds=1 degenerates to exact per-round convergence detection and
  gossip trajectories are BITWISE identical to the single-device engines;
- at larger fused chunks (CR), convergence is detected at the first
  super-step boundary at/after the true round, never before;
- push-sum follows the single-device trajectory to float tolerance over a
  fixed round budget and conserves mass;
- the plan shrinks CR until halo and VMEM constraints fit, and refuses
  configurations with no exact plan (implicit topologies, indivisible
  layouts) with the reason.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.parallel.fused_sharded import (
    plan_fused_sharded,
    run_fused_sharded,
)
from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh

# Interpret-mode Pallas oracle: bitwise engine validation that cannot
# fit the ROADMAP tier-1 wall-clock budget on a CPU-only container (the
# kernels run under the Pallas interpreter). Full-suite / TPU runs
# execute it: `pytest tests/` (no -m filter) or `pytest -m slow`.
pytestmark = pytest.mark.slow

# torus g=50: padded layout 1024 rows -> two 512-row shards.
N = 125000


def _grab(final, tag):
    def f(rounds, state):
        final[tag] = state
    return f


def test_gossip_cr1_bitwise_vs_single_device():
    topo = build_topology("torus3d", N)
    final = {}
    r1 = run(topo, SimConfig(n=N, topology="torus3d", algorithm="gossip",
                             engine="chunked", max_rounds=3000),
             on_chunk=_grab(final, "c"))
    r2 = run(topo, SimConfig(n=N, topology="torus3d", algorithm="gossip",
                             engine="fused", n_devices=2, chunk_rounds=1,
                             max_rounds=3000),
             on_chunk=_grab(final, "f"))
    assert r1.rounds == r2.rounds
    assert r1.converged_count == r2.converged_count
    for f in ("count", "active", "conv"):
        a = np.asarray(getattr(final["c"], f))
        b = np.asarray(getattr(final["f"], f))[:N]
        assert (a == b).all(), f


def test_gossip_cr_adaptive_converges_at_boundary():
    topo = build_topology("torus3d", N)
    r1 = run(topo, SimConfig(n=N, topology="torus3d", algorithm="gossip",
                             engine="chunked", max_rounds=3000))
    r3 = run(topo, SimConfig(n=N, topology="torus3d", algorithm="gossip",
                             engine="fused", n_devices=2, chunk_rounds=8,
                             max_rounds=3000))
    cfg = SimConfig(n=N, topology="torus3d", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=8)
    plan = plan_fused_sharded(build_topology("torus3d", N), cfg, 2)
    assert not isinstance(plan, str)
    cr = plan[2]
    assert r3.converged
    # First super-step boundary at/after the true convergence round.
    assert r1.rounds <= r3.rounds <= r1.rounds + cr


def test_pushsum_fixed_rounds_trajectory_and_mass():
    topo = build_topology("torus3d", N)
    final = {}
    rp1 = run(topo, SimConfig(n=N, topology="torus3d", algorithm="push-sum",
                              engine="chunked", max_rounds=64, chunk_rounds=64),
              on_chunk=_grab(final, "c"))
    rp2 = run(topo, SimConfig(n=N, topology="torus3d", algorithm="push-sum",
                              engine="fused", n_devices=2, chunk_rounds=8,
                              max_rounds=64), on_chunk=_grab(final, "f"))
    assert rp1.rounds == rp2.rounds == 64
    a, b = final["c"], final["f"]
    np.testing.assert_allclose(np.asarray(a.s), np.asarray(b.s)[:N],
                               rtol=2e-5, atol=1e-3)
    np.testing.assert_allclose(np.asarray(a.w), np.asarray(b.w)[:N],
                               rtol=2e-5, atol=1e-6)
    sm = float(np.asarray(b.s, np.float64)[:N].sum())
    true = N * (N - 1) / 2
    assert abs(sm - true) / true < 1e-5
    wm = float(np.asarray(b.w, np.float64)[:N].sum())
    assert abs(wm - N) / N < 1e-5


def test_plan_gating():
    cfg = SimConfig(n=N, topology="torus3d", algorithm="gossip",
                    engine="fused", n_devices=2)
    # implicit topology
    assert "displacement" in plan_fused_sharded(
        build_topology("full", 1024), cfg, 2
    )
    # layout indivisible into whole tiles per device
    assert "tiles per device" in plan_fused_sharded(
        build_topology("torus3d", N), cfg, 3
    )
    # runner surfaces the reason
    bad = SimConfig(n=1024, topology="full", algorithm="gossip",
                    engine="fused", n_devices=2)
    with pytest.raises(ValueError, match="unavailable"):
        run(build_topology("full", 1024), bad)


def test_ring_eight_devices_counts_match():
    # Full 8-device mesh (shards of 512 rows need n >= 8*65536); bounded
    # rounds — the oracle is count equality with the single-device path.
    n = 8 * 65536
    topo = build_topology("ring", n)
    r1 = run(topo, SimConfig(n=n, topology="ring", algorithm="gossip",
                             engine="chunked", max_rounds=60))
    r8 = run(topo, SimConfig(n=n, topology="ring", algorithm="gossip",
                             engine="fused", n_devices=8, chunk_rounds=1,
                             max_rounds=60))
    assert r1.rounds == r8.rounds
    assert r1.converged_count == r8.converged_count


def test_pushsum_global_exact_vs_chunked_sharded():
    # VERDICT r4 #8: termination='global' in the VMEM lattice composition —
    # the psum'd per-round middle unstable vector names the verdict round
    # and the capped deterministic rerun lands the state there, so the stop
    # round is EXACT at CR > 1, matching the chunked sharded global path.
    base = dict(n=N, topology="torus3d", algorithm="push-sum",
                termination="global", delta=1e-1, n_devices=2,
                max_rounds=2000)
    topo = build_topology("torus3d", N)
    a = run(topo, SimConfig(engine="chunked", chunk_rounds=64, **base))
    assert a.converged and a.rounds > 1
    # Through the runner dispatch (not run_fused_sharded directly): this
    # also pins that engine='fused' + n_devices>1 + global ROUTES to the
    # composition instead of the old loud raise.
    b = run(topo, SimConfig(engine="fused", chunk_rounds=8, **base))
    assert b.converged
    assert a.rounds == b.rounds, (a.rounds, b.rounds)
    assert b.converged_count == N


def test_overlap_deferred_verdict_exact_rounds_and_state():
    # The overlapped schedule (parallel/overlap.py) on a CONVERGING run:
    # the verdict psum is deferred one super-step and resolved mid-dispatch
    # (stride = CR*8, so the fire is interior), yet rounds, outcome, and
    # the final planes must be bitwise the serial schedule's — the
    # double-buffer rollback discards the speculative super-step unobserved.
    topo = build_topology("torus3d", N)
    final, res = {}, {}
    for ov in (True, False):
        cfg = SimConfig(n=N, topology="torus3d", algorithm="gossip",
                        engine="fused", n_devices=2, chunk_rounds=8,
                        max_rounds=3000, overlap_collectives=ov)
        res[ov] = run_fused_sharded(topo, cfg, mesh=make_mesh(2),
                                    on_chunk=_grab(final, ov))
    assert res[True].converged and res[False].converged
    assert res[True].rounds == res[False].rounds
    assert res[True].outcome == res[False].outcome
    for f in ("count", "active", "conv"):
        a = np.asarray(getattr(final[True], f))
        b = np.asarray(getattr(final[False], f))
        assert (a == b).all(), f


def test_overlap_stall_watchdog_unchanged():
    # Stall-watchdog runs consult retired boundaries; under the overlapped
    # schedule the retired planes are the rolled-back exact states, so the
    # watchdog must fire at the identical boundary with outcome="stalled".
    topo = build_topology("torus3d", N)
    res = {}
    for ov in (True, False):
        cfg = SimConfig(n=N, topology="torus3d", algorithm="gossip",
                        engine="fused", n_devices=2, chunk_rounds=2,
                        rumor_threshold=10**6, stall_chunks=2,
                        max_rounds=400, overlap_collectives=ov)
        res[ov] = run_fused_sharded(topo, cfg, mesh=make_mesh(2))
    assert res[True].outcome == res[False].outcome == "stalled"
    assert res[True].rounds == res[False].rounds


def test_gossip_grid2d_cr1_bitwise():
    # Non-wrap lattice: the engine's blend handles boundary-truncated
    # displacement classes too, not just wrap topologies.
    n = 131044  # 362^2 -> 1024-row layout -> two 512-row shards
    topo = build_topology("grid2d", n)
    r1 = run(topo, SimConfig(n=n, topology="grid2d", algorithm="gossip",
                             engine="chunked", max_rounds=5000))
    r2 = run(topo, SimConfig(n=n, topology="grid2d", algorithm="gossip",
                             engine="fused", n_devices=2, chunk_rounds=1,
                             max_rounds=5000))
    assert r1.rounds == r2.rounds
    assert r1.converged_count == r2.converged_count
