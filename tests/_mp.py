"""Reusable N-process jax.distributed spawn harness (ISSUE 15).

Generalizes tests/test_multiprocess.py's original two-process spawner into
the one helper every cross-process parity pin uses: spawn N OS processes
of the public CLI over a gloo coordinator, join them, skip-gate on
runtimes whose jaxlib CPU client has no cross-process collectives, and
pass any OTHER child failure through loudly with both processes' logs.

scripts/multihost_smoke.py drives the same flow outside pytest (the
multihost-smoke CI job), via ``spawn_procs``'s SkipUnsupported signal.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

# Older jaxlib CPU clients have no cross-process collectives at all (no
# gloo); the child dies with exactly this XLA error. An explicit skip gate
# keeps the suite honest on such runtimes — any OTHER child failure still
# fails the test.
NO_CPU_MULTIPROCESS = "aren't implemented on the CPU backend"


class SkipUnsupported(RuntimeError):
    """The runtime has no CPU multiprocess collectives — callers outside
    pytest (scripts/multihost_smoke.py) catch this and report SKIP."""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_one(pid: int, n_procs: int, port: int, args: list[str],
               jsonl: Path, devices: int):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "JAX_PLATFORMS", "XLA_FLAGS")}
    # A clean JAX env: repo importable, no remote-TPU site hook, CPU only.
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [
        sys.executable, "-m", "cop5615_gossip_protocol_tpu", *args,
        "--platform", "cpu", "--devices", str(devices),
        "--coordinator", f"127.0.0.1:{port}",
        "--num-processes", str(n_procs), "--process-id", str(pid),
        "--jsonl", str(jsonl),
    ]
    return subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def spawn_procs(tmp_path: Path, args: list[str], *, n_procs: int = 2,
                devices: int = 8, expect_rc=(0,), timeout: int = 300):
    """Run ``args`` through the CLI as ``n_procs`` coordinated OS
    processes sharing one ``devices``-wide global mesh.

    Returns (lead_record, logs): the LEAD process's last --jsonl record
    plus every process's combined stdout/stderr text. Raises
    SkipUnsupported when the runtime lacks gloo CPU collectives; asserts
    (with all logs) when any child exits outside ``expect_rc`` — a
    non-lead crash can never hide behind a healthy lead."""
    port = free_port()
    outs = [tmp_path / f"rec{pid}.jsonl" for pid in range(n_procs)]
    procs = [
        _spawn_one(pid, n_procs, port, args, outs[pid], devices)
        for pid in range(n_procs)
    ]
    logs = []
    for pr in procs:
        try:
            out_bytes, _ = pr.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for p2 in procs:
                p2.kill()
            raise
        logs.append(out_bytes.decode(errors="replace"))
    if any(NO_CPU_MULTIPROCESS in log for log in logs):
        raise SkipUnsupported(
            "this jaxlib's CPU backend has no multiprocess collectives "
            f"({NO_CPU_MULTIPROCESS!r})"
        )
    bad = [
        (i, pr.returncode) for i, pr in enumerate(procs)
        if pr.returncode not in expect_rc
    ]
    assert not bad, (bad, logs)
    return json.loads(outs[0].read_text().splitlines()[-1]), logs


def spawn_pair(tmp_path: Path, args: list[str], *, expect_rc=(0,),
               timeout: int = 300, devices: int = 8):
    """Two-process form — the shape every current pin uses. Translates
    SkipUnsupported into a pytest skip."""
    import pytest

    try:
        return spawn_procs(
            tmp_path, args, n_procs=2, devices=devices,
            expect_rc=expect_rc, timeout=timeout,
        )
    except SkipUnsupported as e:
        pytest.skip(str(e))
