"""Gossip kernel vs a NumPy oracle, plus threshold quirk Q2, converged-node
behavior Q3, suppression (the race-free recast of the reference's shared
dictionary C6), and leader-kickoff variants (C13)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
from cop5615_gossip_protocol_tpu.models import gossip as G


def np_round(count, active, conv, targets, send_ok, suppress, threshold):
    sending = active & send_ok
    if suppress:
        sending = sending & ~conv[targets]
    inbox = np.zeros_like(count)
    np.add.at(inbox, targets, sending.astype(np.int32))
    count_new = count + inbox
    active_new = active | (inbox > 0)
    conv_new = count_new >= threshold
    return count_new, active_new, conv_new


@pytest.mark.parametrize("suppress", [False, True])
def test_round_matches_numpy_oracle(suppress):
    rng = np.random.default_rng(1)
    n = 41
    count = rng.integers(0, 12, n).astype(np.int32)
    active = count > 0
    conv = count >= 10
    targets = rng.integers(0, n, n).astype(np.int32)
    send_ok = rng.random(n) < 0.8

    state = G.GossipState(jnp.asarray(count), jnp.asarray(active), jnp.asarray(conv))
    out = G.round_from_targets(
        state, jnp.asarray(targets), jnp.asarray(send_ok), n, 10, suppress
    )
    ec, ea, ev = np_round(count, active, conv, targets, send_ok, suppress, 10)
    np.testing.assert_array_equal(np.asarray(out.count), ec)
    np.testing.assert_array_equal(np.asarray(out.active), ea)
    np.testing.assert_array_equal(np.asarray(out.conv), ev)


@pytest.mark.parametrize("kind", ["full", "grid2d", "imp3d", "imp2d", "torus3d", "ring"])
def test_converges(kind):
    cfg = SimConfig(n=256, topology=kind, algorithm="gossip", max_rounds=100_000)
    topo = build_topology(kind, 256, seed=0)
    r = run(topo, cfg)
    assert r.converged
    assert r.converged_count == topo.n


def test_rumor_threshold_q2():
    # Honest: converge at 10 receipts. Reference: the `= 10` check precedes
    # the increment (program.fs:102-105) — 11th receipt.
    assert SimConfig(n=8).resolved_rumor_target == 10
    assert SimConfig(n=8, semantics="reference").resolved_rumor_target == 11


def test_converged_nodes_keep_sending_q3():
    # Nothing stops a converged node's send loop (program.fs:89-95).
    n = 3
    state = G.GossipState(
        count=jnp.asarray([10, 0, 0], jnp.int32),
        active=jnp.asarray([True, False, False]),
        conv=jnp.asarray([True, False, False]),
    )
    targets = jnp.asarray([1, 0, 0], jnp.int32)
    out = G.round_from_targets(state, targets, jnp.ones(n, bool), n, 10, False)
    assert int(out.count[1]) == 1  # converged node 0 still delivered


def test_suppression_blocks_sends_to_converged():
    # The dictionary probe at program.fs:92, as a mask on last round's conv.
    n = 2
    state = G.GossipState(
        count=jnp.asarray([1, 10], jnp.int32),
        active=jnp.asarray([True, True]),
        conv=jnp.asarray([False, True]),
    )
    targets = jnp.asarray([1, 0], jnp.int32)
    out = G.round_from_targets(state, targets, jnp.ones(n, bool), n, 10, True)
    assert int(out.count[1]) == 10  # send to converged node 1 suppressed
    assert int(out.count[0]) == 2  # node 1 (converged) still sends, Q3


def test_leader_kickoff_counts_receipt_only_for_full_reference():
    # C13: `full` starts the leader with CallChildActor (program.fs:218) —
    # counts as receipt #1; other topologies use ActivateChildActor.
    s_full = G.init_state(4, jnp.int32(2), leader_counts_receipt=True)
    s_line = G.init_state(4, jnp.int32(2), leader_counts_receipt=False)
    assert int(s_full.count[2]) == 1 and int(s_line.count[2]) == 0
    assert bool(s_full.active[2]) and bool(s_line.active[2])


def test_rumor_spreads_from_single_leader():
    cfg = SimConfig(n=100, topology="line", algorithm="gossip", max_rounds=50_000)
    topo = build_topology("line", 100)
    r = run(topo, cfg)
    # On an honest line without suppression every node eventually converges.
    assert r.converged and r.converged_count == 100


def test_determinism_and_seed_sensitivity():
    import jax

    from cop5615_gossip_protocol_tpu.models.runner import draw_leader
    from cop5615_gossip_protocol_tpu.ops import sampling

    topo = build_topology("full", 128)
    r1 = run(topo, SimConfig(n=128, topology="full", algorithm="gossip", seed=7))
    r2 = run(topo, SimConfig(n=128, topology="full", algorithm="gossip", seed=7))
    assert r1.rounds == r2.rounds
    # Different seeds must yield different random streams: leader draw and
    # round-0 partner bits both derive from the seed.
    cfg7 = SimConfig(n=128, topology="full", algorithm="gossip", seed=7)
    cfg8 = SimConfig(n=128, topology="full", algorithm="gossip", seed=8)
    k7, k8 = jax.random.PRNGKey(7), jax.random.PRNGKey(8)
    bits7 = sampling.uniform_bits(sampling.round_key(k7, 0), 128)
    bits8 = sampling.uniform_bits(sampling.round_key(k8, 0), 128)
    assert (bits7 != bits8).any()
    leaders = {int(draw_leader(k, topo, cfg7)) for k in (k7, k8)}
    assert leaders  # draw is valid under both seeds
    assert all(0 <= ld < 128 for ld in leaders)


def test_typed_and_legacy_keys_share_a_trajectory():
    # ops/sampling.key_split passes the default threefry key through as raw
    # uint32 data; a new-style typed key (jax.random.key), the classic
    # PRNGKey, and the raw data itself must all drive the identical
    # trajectory — a silent stream split here would break resume.
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run

    cfg = SimConfig(n=144, topology="grid2d", algorithm="gossip")
    topo = build_topology("grid2d", 144)
    r_prng = run(topo, cfg, key=jax.random.PRNGKey(5))
    r_typed = run(topo, cfg, key=jax.random.key(5))
    r_raw = run(topo, cfg, key=jax.random.key_data(jax.random.PRNGKey(5)))
    assert r_prng.rounds == r_typed.rounds == r_raw.rounds
    assert (
        r_prng.converged_count
        == r_typed.converged_count
        == r_raw.converged_count
    )
