"""Halo-exchange sharded delivery (parallel/halo.py).

Pins: (a) the host-side plan accepts exactly the topologies it can serve
exactly; (b) halo_roll is a true global circular roll under shard_map;
(c) sharded trajectories through the halo path are bit-identical to the
single-device stencil path; (d) padded populations are exact for non-wrap
topologies and refused for wrap topologies; (e) delivery='stencil' under
sharding fails loudly when no exact plan exists.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
from cop5615_gossip_protocol_tpu.parallel import halo
from cop5615_gossip_protocol_tpu.parallel.mesh import NODE_AXIS, make_mesh
from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded
from cop5615_gossip_protocol_tpu.utils import compat


# --- plan_halo ------------------------------------------------------------


def test_plan_exists_for_offset_topologies():
    for kind, n in [("line", 512), ("ring", 512), ("grid2d", 1024),
                    ("torus3d", 512), ("grid3d", 512)]:
        topo = build_topology(kind, n)
        plan = halo.plan_halo(topo, 8)
        assert plan is not None, kind
        assert plan.halo_width <= plan.n_loc


def test_plan_none_for_irregular_and_implicit():
    assert halo.plan_halo(build_topology("imp3d", 512), 8) is None
    assert halo.plan_halo(build_topology("full", 512), 8) is None


def test_torus_halo_is_narrow():
    # Signed offsets turn wrap displacements (mod ~n) into a few lattice
    # rows: for g=8 (n=512) the widest roll is g^2 = 64, not ~n.
    topo = build_topology("torus3d", 512)
    plan = halo.plan_halo(topo, 8)
    assert plan.halo_width == 64


def test_plan_padded_population_wrap_vs_nonwrap():
    # line has no global-wrap edges: padded population stays exact.
    assert halo.plan_halo(build_topology("line", 1001), 8) is not None
    # ring's wrap edge n-1 -> 0 would land in a pad slot: refused.
    assert halo.plan_halo(build_topology("ring", 1001), 8) is None
    # ...but an evenly dividing ring population is exact.
    assert halo.plan_halo(build_topology("ring", 1000), 8) is not None


def test_plan_halo_wider_than_shard_refused():
    # grid2d side ~ sqrt(n): at n=64 (side 8, halo 8) over 8 devices
    # n_loc = 8, so the plan just fits; over 16 devices it would not —
    # emulate by asking for more devices than lanes per shard.
    topo = build_topology("grid2d", 64)
    assert halo.plan_halo(topo, 8) is not None
    assert halo.plan_halo(topo, 16) is None


# --- halo_roll ------------------------------------------------------------


@pytest.mark.parametrize("s", [1, -1, 7, -7, 64, -64])
def test_halo_roll_is_global_circular_roll(s):
    n = 512
    mesh = make_mesh(8)
    x = np.arange(n, dtype=np.float32)

    def f(x_loc):
        return halo.halo_roll(x_loc, s, NODE_AXIS, 8)

    rolled = jax.jit(
        compat.shard_map(
            f, mesh=mesh, in_specs=P(NODE_AXIS), out_specs=P(NODE_AXIS)
        )
    )(x)
    np.testing.assert_array_equal(np.asarray(rolled), np.roll(x, s))


def test_halo_roll_single_device():
    x = jnp.arange(16.0)
    np.testing.assert_array_equal(
        np.asarray(halo.halo_roll(x, 3, NODE_AXIS, 1)), np.roll(np.arange(16.0), 3)
    )


# --- global_roll_dynamic (pool-roll delivery) -----------------------------


@pytest.mark.parametrize("r", [0, 1, 63, 64, 65, 200, 511])
def test_global_roll_dynamic_matches_roll(r):
    # Traced roll amount: r enters as a replicated scalar argument, so one
    # compiled program serves every per-round pool offset.
    n = 512
    mesh = make_mesh(8)
    x = np.arange(2 * n, dtype=np.float32).reshape(2, n)  # stacked channels

    def f(x_loc, r):
        return halo.global_roll_dynamic(x_loc, r, NODE_AXIS, 8)

    rolled = jax.jit(
        compat.shard_map(
            f, mesh=mesh, in_specs=(P(None, NODE_AXIS), P()),
            out_specs=P(None, NODE_AXIS),
        )
    )(x, jnp.int32(r))
    np.testing.assert_array_equal(np.asarray(rolled), np.roll(x, r, axis=1))


def test_global_roll_dynamic_single_device():
    x = jnp.arange(16.0)
    np.testing.assert_array_equal(
        np.asarray(halo.global_roll_dynamic(x, jnp.int32(5), NODE_AXIS, 1)),
        np.roll(np.arange(16.0), 5),
    )


def test_pool_roll_pushsum_bitwise_matches_single_device():
    # Same masked values, same static pool-slot accumulation order → the
    # sharded pool-roll float trajectory is bitwise the single-device one.
    n = 1024
    cfg = SimConfig(n=n, topology="full", algorithm="push-sum",
                    delivery="pool", pool_size=4, max_rounds=50_000)
    topo = build_topology("full", n)

    final = {}

    def grab(tag):
        def on_chunk(rounds, state):
            final[tag] = state
        return on_chunk

    r1 = run(topo, cfg, on_chunk=grab("single"))
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8), on_chunk=grab("sharded"))
    assert r8.rounds == r1.rounds
    np.testing.assert_array_equal(
        np.asarray(final["single"].s), np.asarray(final["sharded"].s)[:n]
    )
    np.testing.assert_array_equal(
        np.asarray(final["single"].w), np.asarray(final["sharded"].w)[:n]
    )


def test_pool_roll_gossip_suppression_bitwise():
    # Suppression is receiver-side (models/gossip.absorb) — purely local on
    # every path; sharded pool-roll trajectories must still match the
    # single-device pool path exactly.
    n = 1024
    cfg = SimConfig(n=n, topology="full", algorithm="gossip",
                    delivery="pool", suppress_converged=True, seed=3)
    topo = build_topology("full", n)
    r1 = run(topo, cfg)
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert r8.rounds == r1.rounds
    assert r8.converged_count == r1.converged_count


# --- end-to-end bit-identity ---------------------------------------------


@pytest.mark.parametrize("kind,n", [("torus3d", 512), ("line", 1001), ("grid2d", 1024)])
def test_gossip_halo_matches_single_device_bitwise(kind, n):
    cfg = SimConfig(n=n, topology=kind, algorithm="gossip", seed=5)
    topo = build_topology(kind, n, seed=5)
    assert halo.plan_halo(topo, 8) is not None  # the path under test
    r1 = run(topo, cfg)
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert r8.rounds == r1.rounds
    assert r8.converged_count == r1.converged_count
    assert r8.converged and r1.converged


def test_pushsum_halo_matches_single_device_bitwise():
    # Same static accumulation order as the single-device stencil path →
    # float trajectories are bitwise identical, not merely close.
    n = 512
    cfg = SimConfig(n=n, topology="torus3d", algorithm="push-sum",
                    dtype="float32", max_rounds=50_000)
    topo = build_topology("torus3d", n)

    final = {}

    def grab(tag):
        def on_chunk(rounds, state):
            final[tag] = state
        return on_chunk

    r1 = run(topo, cfg, on_chunk=grab("single"))
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8), on_chunk=grab("sharded"))
    assert r8.rounds == r1.rounds
    np.testing.assert_array_equal(
        np.asarray(final["single"].s), np.asarray(final["sharded"].s)[:n]
    )
    np.testing.assert_array_equal(
        np.asarray(final["single"].w), np.asarray(final["sharded"].w)[:n]
    )


def test_sharded_suppression_halo_path_bitwise():
    # Reference-semantics gossip on a halo topology: suppression is enabled
    # (the registry probe semantics) and applied receiver-side on both paths.
    n = 511  # population 512 after the Q1 extra actor → divides 8 devices
    cfg = SimConfig(n=n, topology="line", algorithm="gossip",
                    semantics="reference", seed=2)
    topo = build_topology("line", n, semantics="reference")
    assert halo.plan_halo(topo, 8) is not None
    r1 = run(topo, cfg)
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert r8.rounds == r1.rounds
    assert r8.converged_count == r1.converged_count


# --- fail-loudly + fallback ----------------------------------------------


def test_sharded_stencil_request_fails_loudly_without_plan():
    topo = build_topology("imp3d", 512)
    cfg = SimConfig(n=512, topology="imp3d", algorithm="gossip",
                    delivery="stencil", n_devices=8)
    with pytest.raises(ValueError, match="halo"):
        run(topo, cfg)


@pytest.mark.slow
def test_two_process_batched_wire_matches_per_class_bitwise(tmp_path):
    # Batched vs per-class halo wires over REAL two-OS-process gloo
    # collectives (the packed ppermute pair crosses the process boundary):
    # both schedules must reproduce the single-process mesh bit-for-bit —
    # gossip state is integer and the random stream is process-count-
    # invariant, so rounds and converged counts pin the delivery exactly.
    import json
    import os
    import subprocess
    import sys as _sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    n = 4096  # 16^3 torus: halo delivery, 10 offset classes
    ref = run_sharded(
        build_topology("torus3d", n),
        SimConfig(n=n, topology="torus3d", algorithm="gossip", n_devices=8),
        mesh=make_mesh(8),
    )
    assert ref.converged

    def pair(overlap: str, port: int):
        outs = [tmp_path / f"{overlap}{pid}.jsonl" for pid in range(2)]
        env = {k: v for k, v in os.environ.items()
               if k not in ("PYTHONPATH", "JAX_PLATFORMS", "XLA_FLAGS")}
        env["PYTHONPATH"] = str(repo)
        env["JAX_PLATFORMS"] = "cpu"
        procs = [
            subprocess.Popen(
                [_sys.executable, "-m", "cop5615_gossip_protocol_tpu",
                 str(n), "torus3d", "gossip", "--platform", "cpu",
                 "--devices", "8", "--overlap-collectives", overlap,
                 "--coordinator", f"127.0.0.1:{port}",
                 "--num-processes", "2", "--process-id", str(pid),
                 "--jsonl", str(outs[pid])],
                cwd=repo, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            )
            for pid in range(2)
        ]
        try:
            logs = [p.communicate(timeout=300)[0].decode(errors="replace")
                    for p in procs]
        finally:
            # A hung coordinator barrier (one child dead at startup) must
            # not leak the survivor holding the port across test runs.
            for p in procs:
                if p.poll() is None:
                    p.kill()
        if any("aren't implemented on the CPU backend" in s for s in logs):
            pytest.skip("this jaxlib's CPU backend has no multiprocess "
                        "collectives")
        assert all(p.returncode == 0 for p in procs), logs
        return json.loads(outs[0].read_text().splitlines()[-1])

    base = 21000 + (os.getpid() + 616) % 9000
    for i, overlap in enumerate(("on", "off")):
        rec = pair(overlap, base + i)
        assert rec["rounds"] == ref.rounds, overlap
        assert rec["converged_count"] == ref.converged_count, overlap


def test_ring_padded_auto_falls_back_to_scatter():
    # No exact halo plan (wrap edges + padding) → auto silently uses the
    # scatter + psum_scatter path and still converges on real nodes only.
    n = 1001
    cfg = SimConfig(n=n, topology="ring", algorithm="gossip", seed=1)
    topo = build_topology("ring", n)
    assert halo.plan_halo(topo, 8) is None
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8))
    r1 = run(topo, cfg)
    assert r8.converged
    assert r8.rounds == r1.rounds  # scatter path is also stream-identical
