"""Distributed-without-a-cluster (SURVEY.md §4): the same shard_map collective
program runs on 8 virtual CPU devices. Sharded trajectories must match the
single-device runner — exactly for gossip's integer counts, up to float
summation order for push-sum."""

import jax
import numpy as np
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh
from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("kind", ["full", "torus3d"])
def test_gossip_sharded_matches_single_device_exactly(kind):
    # n divisible by 8 → identical random streams → identical integer
    # trajectories, device count notwithstanding.
    n = 512
    cfg = SimConfig(n=n, topology=kind, algorithm="gossip", seed=3)
    topo = build_topology(kind, n, seed=3)
    r1 = run(topo, cfg)
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert r8.rounds == r1.rounds
    assert r8.converged_count == r1.converged_count
    assert r8.converged and r1.converged


@pytest.mark.parametrize("kind", ["full", "grid2d", "imp2d"])
def test_pushsum_sharded_matches_single_device(kind):
    n = 256
    cfg = SimConfig(
        n=n, topology=kind, algorithm="push-sum", dtype="float64",
        max_rounds=100_000,
    )
    topo = build_topology(kind, n)
    r1 = run(topo, cfg)
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert r8.converged and r1.converged
    # Summation order may differ; at f64 the trajectories stay aligned.
    assert abs(r8.rounds - r1.rounds) <= max(2, r1.rounds // 100)
    assert r8.estimate_mae < 1e-6 * n


def test_padding_population_not_divisible():
    # 250 nodes over 8 devices → 6 padded slots: must run, converge, and
    # count only real nodes.
    n = 250
    cfg = SimConfig(n=n, topology="full", algorithm="push-sum", dtype="float64")
    topo = build_topology("full", n)
    r = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert r.population == n
    assert r.converged and r.converged_count == n
    assert r.estimate_mae < 1e-6


def test_sharded_suppression_all_gather_path():
    # Reference-mode gossip exercises the all_gather converged-vector probe.
    n = 255  # population 256 after the Q1 extra actor
    cfg = SimConfig(n=n, topology="full", algorithm="gossip", semantics="reference")
    topo = build_topology("full", n, semantics="reference")
    r = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert r.population == 256 and r.target_count == 255
    assert r.converged


def test_run_dispatches_on_n_devices():
    n = 256
    cfg = SimConfig(n=n, topology="full", algorithm="gossip", n_devices=8)
    topo = build_topology("full", n)
    r = run(topo, cfg)
    cfg1 = SimConfig(n=n, topology="full", algorithm="gossip")
    r1 = run(topo, cfg1)
    assert r.rounds == r1.rounds and r.converged


def test_mesh_validation():
    with pytest.raises(ValueError):
        make_mesh(99)


def test_pushsum_mass_conserved_under_sharding():
    n = 256
    cfg = SimConfig(
        n=n, topology="grid2d", algorithm="push-sum", dtype="float64",
        chunk_rounds=64, max_rounds=64,  # stop mid-flight to inspect mass
    )
    topo = build_topology("grid2d", n)
    seen = {}

    def on_chunk(rounds, state):
        seen["s"] = float(np.asarray(state.s).sum())
        seen["w"] = float(np.asarray(state.w).sum())

    run_sharded(topo, cfg, mesh=make_mesh(8), on_chunk=on_chunk)
    assert seen["s"] == pytest.approx(n * (n - 1) / 2, rel=1e-12)
    assert seen["w"] == pytest.approx(n, rel=1e-12)  # no padding at n=256

def test_sharded_resume_continues_stream(tmp_path):
    # Interrupt a sharded run mid-flight, resume, land on the uninterrupted
    # round count (absolute-round PRNG indexing).
    from cop5615_gossip_protocol_tpu.utils import checkpoint as ckpt

    n = 256
    base = dict(n=n, topology="grid2d", algorithm="push-sum", dtype="float64",
                chunk_rounds=200)
    topo = build_topology("grid2d", n)
    full = run_sharded(topo, SimConfig(**base), mesh=make_mesh(8))
    assert full.converged and full.rounds > 400

    half = (full.rounds // 2 // 200) * 200
    saved = {}

    def on_chunk(rounds, state):
        saved["state"], saved["rounds"] = state, rounds

    cfg_half = SimConfig(**base, max_rounds=half)
    run_sharded(topo, cfg_half, mesh=make_mesh(8), on_chunk=on_chunk)
    p = tmp_path / "sharded.npz"
    # Persist through the real checkpoint layer (unpadded n==256 here).
    ckpt.save(p, saved["state"], saved["rounds"], cfg_half)
    state, rounds, _ = ckpt.load(p)

    resumed = run_sharded(topo, SimConfig(**base), mesh=make_mesh(8),
                          start_state=state, start_round=rounds)
    assert resumed.converged
    assert resumed.rounds == full.rounds


def test_nondivisible_population_requires_partitionable_threefry():
    # The padded full-length draw equals the single-device stream only under
    # the position-wise partitionable threefry; with the flag off the runner
    # must refuse a non-divisible population rather than silently diverge.
    # Subprocess: the flag must be set before any trace caches exist.
    import subprocess
    import sys

    code = """
import os
# Virtual-device request via XLA_FLAGS (works on every JAX this repo
# supports; the jax_num_cpu_devices config option is newer than some
# runtimes — utils/compat.set_host_device_count).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", False)
import sys
sys.path.insert(0, {root!r})
from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded
try:
    run_sharded(build_topology("full", 1001),
                SimConfig(n=1001, topology="full", algorithm="gossip",
                          max_rounds=4, n_devices=8))
except ValueError as e:
    assert "jax_threefry_partitionable" in str(e), e
    print("GUARDED")
    raise SystemExit(0)
raise SystemExit("no error raised")
""".format(root=str(__import__("pathlib").Path(__file__).resolve().parents[1]))
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300,
    )
    assert out.returncode == 0, out.stderr
    assert "GUARDED" in out.stdout


def test_pushsum_scatter_path_f32_is_quality_equivalent():
    # The scatter + psum_scatter delivery reassociates partial float sums;
    # at float32 the ulp drift, amplified by the term-counter reset, shifts
    # round counts (measured up to tens of percent) — the contract on this
    # path is convergence-set and estimate-quality equivalence, not round
    # equality (float64 restores alignment: see
    # test_pushsum_sharded_matches_single_device).
    cfg = SimConfig(n=322, topology="imp2d", algorithm="push-sum",
                    seed=22875, max_rounds=200_000)
    topo = build_topology("imp2d", 322, seed=22875)
    r1 = run(topo, cfg)
    r8 = run_sharded(topo, cfg, mesh=make_mesh(8))
    assert r1.converged and r8.converged
    assert r1.converged_count == r8.converged_count == topo.n
    assert abs(r1.estimate_mae - r8.estimate_mae) < 0.01
