"""Tier-1 unit pins for the in-kernel halo delivery of the HBM-streaming
x sharded composition (ISSUE 9): interior-first tile ordering, the
boundary-tile split, the one-sweep delivery plan over the extended ring,
and the DMA/fallback capability selection — all host-side or trace-level,
no Pallas execution (the interpret-mode parity oracles live in
tests/test_fused_hbm_sharded.py, slow-marked).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.ops.fused_pool import build_pool_layout
from cop5615_gossip_protocol_tpu.parallel import halo
from cop5615_gossip_protocol_tpu.parallel.fused_hbm_sharded import (
    _boundary_split,
    _class_sigmas,
    _halo_width_slots,
    _shard_delivery_plan,
    _visit_order,
    _visit_tile,
    run_stencil_hbm_sharded,
)
from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh


def test_visit_order_is_interior_first_permutation():
    for T, b_lo, b_hi in [(8, 1, 1), (8, 2, 3), (4, 1, 2), (4, 2, 2),
                          (16, 3, 1), (2, 1, 1)]:
        order = _visit_order(T, b_lo, b_hi)
        assert sorted(order) == list(range(T)), (T, b_lo, b_hi)
        n_int = T - b_lo - b_hi
        interior = set(range(b_lo, T - b_hi))
        boundary = set(range(b_lo)) | set(range(T - b_hi, T))
        assert set(order[:n_int]) == interior
        assert set(order[n_int:]) == boundary
        # Every interior tile streams BEFORE any boundary tile — the halo
        # drain can sit at position n_int and cover exactly the tiles
        # that can read halo/mirror rows.
        assert all(t in interior for t in order[:n_int])


def test_visit_tile_matches_visit_order_traced():
    for T, b_lo, b_hi in [(8, 1, 1), (8, 2, 3), (4, 2, 2), (16, 3, 1)]:
        want = _visit_order(T, b_lo, b_hi)
        got = [
            int(_visit_tile(jnp.int32(u), T, b_lo, b_hi)) for u in range(T)
        ]
        assert got == want, (T, b_lo, b_hi)


def test_boundary_split_covers_halo_and_mirror_reads():
    for H, PT, T, S in [(128, 256, 8, 21), (192, 512, 4, 21),
                        (96, 2048, 2, 3), (1024, 512, 16, 40),
                        (4096, 256, 8, 10)]:
        b_lo, b_hi = _boundary_split(H, PT, T, S)
        assert 1 <= b_lo <= T
        assert 0 <= b_hi <= T - b_lo
        # Tiles below b_lo / above T - b_hi are the only ones whose reads
        # (own tile +/- the window reach S with alignment slack) can touch
        # the H halo rows at either end — unless the whole shard is
        # boundary (b_lo + b_hi == T).
        if b_lo + b_hi < T:
            assert b_lo * PT >= H + S + 16
            assert b_hi * PT >= H + S + 24


def test_shard_delivery_plan_torus_collapses_to_one_group():
    # torus3d at the interpret-suite population: 10 offset classes, the
    # Z > 0 blend live — over the extended ring BOTH blend variants'
    # window shifts are within the halo width, so the one-sweep plan
    # collapses every need into ONE group window (one fetch + one regen
    # per tile, the single-device engine's economy carried across shards).
    topo = build_topology("torus3d", 125000)
    layout = build_pool_layout(topo.n)
    rows_ext = 512 + 2 * 128
    classes, groups, M, blend = _shard_delivery_plan(
        topo, layout, rows_ext, 256
    )
    assert blend
    assert len(groups) == 1, groups
    assert M == groups[0][1]
    # Every wrap class carries the two-variant blend pair; reads point at
    # the single group.
    for _d, reads in classes:
        assert len(reads) == 2
        assert all(gi == 0 for gi, _e, _sq, _t1 in reads)
    # The group margin covers each read's offset: off <= span + 7 and the
    # off+1 window of PT rows stays inside m_rows.
    sqs = [sq for _d, reads in classes for _gi, _e, sq, _t1 in reads]
    span = max(sqs) - min(sqs)
    assert groups[0][1] >= 256 + span + 16
    # The plan's widest shift agrees with the halo-width home
    # (_class_sigmas) — the two can never drift.
    assert max(abs(s) for s in sqs) <= -(-_halo_width_slots(topo, layout)
                                         // 128) + 1


def test_shard_delivery_plan_nonwrap_single_windows():
    topo = build_topology("grid2d", 131044)
    layout = build_pool_layout(topo.n)
    classes, groups, _M, blend = _shard_delivery_plan(
        topo, layout, 512 + 2 * 128, 256
    )
    assert not blend
    for _d, reads in classes:
        assert len(reads) == 1
        assert reads[0][3] is None  # take1: single-window classes


def test_resolve_halo_transport_capability_matrix():
    auto = SimConfig(n=1000, topology="ring")
    assert auto.halo_dma == "auto"
    assert halo.resolve_halo_transport(auto, "cpu") == "ppermute"
    assert halo.resolve_halo_transport(auto, "tpu") == "dma"
    on = SimConfig(n=1000, topology="ring", halo_dma="on")
    assert halo.resolve_halo_transport(on, "cpu") == "dma"
    off = SimConfig(n=1000, topology="ring", halo_dma="off")
    assert halo.resolve_halo_transport(off, "tpu") == "ppermute"


def test_halo_dma_validated_at_config_time():
    with pytest.raises(ValueError, match="halo_dma"):
        SimConfig(n=1000, topology="ring", halo_dma="bogus")


def test_halo_dma_forced_on_cpu_fails_loudly_at_execution():
    # halo_dma='on' builds the remote-copy kernel, which cannot EXECUTE
    # off-TPU; the run must refuse with a pointer at auto/probe instead of
    # dying inside Mosaic. (The probe hook on the same config is the legal
    # CPU use — tests/test_comm_audit.py exercises it.)
    n = 65536
    topo = build_topology("ring", n)
    cfg = SimConfig(n=n, topology="ring", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=1,
                    max_rounds=8, halo_dma="on")
    with pytest.raises(ValueError, match="TPU"):
        run_stencil_hbm_sharded(topo, cfg, mesh=make_mesh(2))


def test_halo_dma_probe_traces_on_cpu():
    # The capability gate must NOT block the trace-only probe path — the
    # comm audit's hardware-free DMA audit depends on it.
    n = 65536
    topo = build_topology("ring", n)
    cfg = SimConfig(n=n, topology="ring", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=1,
                    max_rounds=8, halo_dma="on")
    seen = {}

    def probe(fn, args, **info):
        seen["jaxpr"] = jax.make_jaxpr(fn)(*args)
        return "probed"

    assert run_stencil_hbm_sharded(
        topo, cfg, mesh=make_mesh(2), probe=probe
    ) == "probed"
    assert "ppermute" not in str(seen["jaxpr"])


def test_transport_knob_keeps_plan_geometry_identical():
    # The plan must be invariant to BOTH scheduling knobs — a geometry
    # (H, CR, PT) that differed across halo_dma or overlap_collectives
    # would break super-step-granular `rounds` interchangeability.
    from cop5615_gossip_protocol_tpu.parallel.fused_hbm_sharded import (
        plan_stencil_hbm_sharded,
    )

    topo = build_topology("torus3d", 125000)
    plans = []
    for hd in ("auto", "on", "off"):
        for ov in (True, False):
            cfg = SimConfig(n=125000, topology="torus3d",
                            algorithm="push-sum", engine="fused",
                            n_devices=2, chunk_rounds=8, halo_dma=hd,
                            overlap_collectives=ov)
            plans.append(plan_stencil_hbm_sharded(topo, cfg, 2)[:4])
    assert all(p == plans[0] for p in plans), plans


def test_class_sigmas_blend_pairs_within_halo_width():
    # The reason ONE group serves both blend variants: signed(-d) and
    # signed(n-d) are both bounded by the halo width for every class.
    topo = build_topology("torus3d", 125000)
    layout = build_pool_layout(topo.n)
    w = _halo_width_slots(topo, layout)
    for _d, s1, s2 in _class_sigmas(topo, layout):
        assert abs(s1) <= w
        if s2 is not None:
            assert abs(s2) <= w


def test_mid_state_noop_on_converged_dispatch():
    # Overshoot contract on the fallback transport: a dispatch at an
    # already-converged state executes zero rounds and returns the planes
    # bitwise (the pipelined driver relies on it). Cheap: ring layout,
    # zero executed rounds, no Pallas round body runs.
    from cop5615_gossip_protocol_tpu.models.gossip import GossipState

    n = 65536
    topo = build_topology("ring", n)
    cfg = SimConfig(n=n, topology="ring", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=2,
                    max_rounds=100)
    counts = np.full(n, 10, np.int32)
    done_state = GossipState(
        count=jnp.asarray(counts),
        active=jnp.zeros(n, bool),
        conv=jnp.ones(n, bool),
    )
    grab = {}
    r = run_stencil_hbm_sharded(
        topo, cfg, mesh=make_mesh(2), start_state=done_state,
        start_round=7, on_chunk=lambda rr, s: grab.update(s=s),
    )
    assert r.rounds == 7
    assert r.converged
    assert r.converged_count == n
    if "s" in grab:
        assert (np.asarray(grab["s"].count) == counts).all()
