"""Fused multi-round Pallas engine (ops/fused.py), run in interpret mode on
CPU. Oracles:

- the in-kernel Threefry must equal jax.random.bits bit-for-bit (the whole
  bit-compatibility story rests on it);
- full runs must match the chunked XLA runner: gossip bitwise (integer
  state), push-sum on rounds/estimates (float32 both paths, same op order);
- eligibility gating must fail loudly.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import fused, sampling

# Interpret-mode Pallas oracle: bitwise engine validation that cannot
# fit the ROADMAP tier-1 wall-clock budget on a CPU-only container (the
# kernels run under the Pallas interpreter). Full-suite / TPU runs
# execute it: `pytest tests/` (no -m filter) or `pytest -m slow`.
pytestmark = pytest.mark.slow


def test_threefry_matches_jax_random():
    key = jax.random.PRNGKey(42)
    kd = jax.random.key_data(key) if key.dtype != jnp.uint32 else key
    for m in [128, 384, 1280]:
        rows = m // 128
        got = np.asarray(
            fused.threefry_bits_2d(kd[0], kd[1], rows, 128)
        ).reshape(-1)
        want = np.asarray(jax.random.bits(key, (m,), jnp.uint32))
        assert (got == want).all(), m


def test_threefry_prefix_property():
    # Padding invariance: first n values of an n_pad draw equal the n draw.
    key = jax.random.PRNGKey(7)
    a = np.asarray(jax.random.bits(key, (300,), jnp.uint32))
    b = np.asarray(jax.random.bits(key, (512,), jnp.uint32))
    assert (a == b[:300]).all()


def test_round_keys_match_sampling():
    key = jax.random.PRNGKey(3)
    keys = np.asarray(fused.round_keys(key, 5, 4))
    for i, r in enumerate(range(5, 9)):
        want = sampling.round_key(key, r)
        want = jax.random.key_data(want) if want.dtype != jnp.uint32 else want
        assert (keys[i] == np.asarray(want)).all()


@pytest.mark.parametrize("kind", ["line", "grid2d", "grid3d"])
def test_fused_gossip_matches_chunked_bitwise(kind):
    n = 144
    results = {}
    for engine in ["chunked", "fused"]:
        cfg = SimConfig(n=n, topology=kind, algorithm="gossip", engine=engine,
                        max_rounds=4000, chunk_rounds=48)
        results[engine] = run(build_topology(kind, n), cfg)
    a, b = results["chunked"], results["fused"]
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count
    assert a.converged and b.converged


def test_fused_gossip_suppression_reference_mode():
    n = 100
    results = {}
    for engine in ["chunked", "fused"]:
        cfg = SimConfig(n=n, topology="line", algorithm="gossip", engine=engine,
                        semantics="reference", max_rounds=6000, chunk_rounds=64)
        results[engine] = run(build_topology("line", n, semantics="reference"), cfg)
    a, b = results["chunked"], results["fused"]
    assert a.rounds == b.rounds and a.converged_count == b.converged_count


def test_fused_pushsum_matches_chunked():
    n = 128  # multiple of 128: no padding, wrap kinds also legal
    results = {}
    for engine in ["chunked", "fused"]:
        cfg = SimConfig(n=n, topology="ring", algorithm="push-sum",
                        dtype="float32", engine=engine,
                        max_rounds=60000, chunk_rounds=256)
        results[engine] = run(build_topology("ring", n), cfg)
    a, b = results["chunked"], results["fused"]
    assert a.converged and b.converged
    # Same f32 op order => identical trajectories up to compiler
    # reassociation; rounds must agree exactly on this scale.
    assert a.rounds == b.rounds
    assert abs(a.estimate_mae - b.estimate_mae) < 1e-3


def test_fused_pushsum_padded_nonwrap():
    n = 49  # grid2d 7x7, padded to 128 in-kernel
    cfg = SimConfig(n=n, topology="grid2d", algorithm="push-sum",
                    dtype="float32", engine="fused",
                    max_rounds=60000, chunk_rounds=256)
    r = run(build_topology("grid2d", n), cfg)
    ref = run(build_topology("grid2d", n),
              SimConfig(n=n, topology="grid2d", algorithm="push-sum",
                        dtype="float32", engine="chunked",
                        max_rounds=60000, chunk_rounds=256))
    assert r.converged and ref.converged
    assert r.rounds == ref.rounds


def test_fused_resume_midway():
    # Chunk-boundary state from a fused run resumes to the same trajectory.
    n = 144
    kind = "grid2d"
    cfg = SimConfig(n=n, topology=kind, algorithm="gossip", engine="fused",
                    max_rounds=4000, chunk_rounds=32)
    topo = build_topology(kind, n)
    snaps = []
    full = run(topo, cfg, on_chunk=lambda r, s: snaps.append((r, s)))
    assert len(snaps) >= 2
    r0, s0 = snaps[0]
    resumed = run(topo, cfg, start_state=jax.tree.map(jnp.asarray, s0), start_round=r0)
    assert resumed.rounds == full.rounds
    assert resumed.converged_count == full.converged_count


def test_fused_support_gating():
    # wrap topology with n not divisible by 128: the v1 whole-array engine
    # refuses (its padded-space rolls would misdeliver); the run() dispatch
    # now falls through to the tiled stencil2 engine instead of raising
    # (tests/test_fused_stencil2.py pins that path).
    topo = build_topology("torus3d", 1000)  # pop 729
    cfg = SimConfig(n=1000, topology="torus3d", algorithm="push-sum",
                    engine="fused")
    assert "128" in fused.fused_support(topo, cfg)
    # implicit full
    cfg = SimConfig(n=64, topology="full", engine="fused")
    with pytest.raises(ValueError, match="fused"):
        run(build_topology("full", 64), cfg)
    # f64
    cfg = SimConfig(n=64, topology="line", engine="fused", dtype="float64")
    with pytest.raises(ValueError, match="float32"):
        run(build_topology("line", 64), cfg)


def test_has_wrap_edges():
    assert fused._has_wrap_edges(build_topology("ring", 100))
    assert not fused._has_wrap_edges(build_topology("line", 100))
    assert not fused._has_wrap_edges(build_topology("grid3d", 64))
    assert fused._has_wrap_edges(build_topology("torus3d", 64))


@pytest.mark.parametrize("chunk_rounds", [5, 100])
def test_chunk_rounds_not_multiple_of_8(chunk_rounds):
    # Regression: SMEM key blocks are padded to 8 rounds with zero keys; the
    # padded grid steps must not execute. Before the cap clamp in chunk_fn,
    # chunk_rounds=5 ran 3 extra rounds per chunk with key (0,0) — identical
    # random bits every chunk — and diverged from the chunked engine.
    n = 144
    results = {}
    for engine, ck in [("chunked", 48), ("fused", chunk_rounds)]:
        cfg = SimConfig(n=n, topology="grid2d", algorithm="gossip",
                        engine=engine, max_rounds=4000, chunk_rounds=ck)
        results[engine] = run(build_topology("grid2d", n), cfg)
    a, b = results["chunked"], results["fused"]
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


def test_fused_rejects_scatter_delivery_and_reference_pushsum():
    # Silent-ignore combinations must fail loudly (fail-loudly contract).
    topo = build_topology("line", 64)
    cfg = SimConfig(n=64, topology="line", algorithm="gossip",
                    engine="fused", delivery="scatter")
    with pytest.raises(ValueError, match="scatter"):
        run(topo, cfg)
    topo_r = build_topology("line", 64, semantics="reference")
    cfg_r = SimConfig(n=64, topology="line", algorithm="push-sum",
                      semantics="reference", engine="fused")
    with pytest.raises(ValueError, match="single-walk"):
        run(topo_r, cfg_r)
    # fused under sharding routes to the fused x sharded composition
    # (parallel/fused_sharded.py); a layout with no exact per-device plan
    # must raise with the reason, not silently run the chunked engine.
    cfg_s = SimConfig(n=64, topology="line", algorithm="gossip",
                      engine="fused", n_devices=8)
    with pytest.raises(ValueError, match="unavailable"):
        run(topo, cfg_s)
    # ...and scatter delivery stays a loud rejection under sharding too.
    cfg_ss = SimConfig(n=125000, topology="torus3d", algorithm="gossip",
                       engine="fused", delivery="scatter", n_devices=2)
    with pytest.raises(ValueError, match="scatter"):
        run(build_topology("torus3d", 125000), cfg_ss)


def test_fused_resume_rejects_non_float32():
    from cop5615_gossip_protocol_tpu.models import pushsum as pushsum_mod
    from cop5615_gossip_protocol_tpu.models.runner import _run_fused

    topo = build_topology("ring", 128)
    cfg = SimConfig(n=128, topology="ring", algorithm="push-sum", engine="fused")
    st64 = pushsum_mod.PushSumState(
        s=jnp.arange(128, dtype=jnp.float64) if jax.config.jax_enable_x64
        else jnp.arange(128, dtype=jnp.float16),
        w=jnp.ones((128,)), term=jnp.zeros((128,), jnp.int32),
        conv=jnp.zeros((128,), bool),
    )
    with pytest.raises(ValueError, match="float32 checkpoint"):
        _run_fused(topo, cfg, jax.random.PRNGKey(0), None, st64, 0, True)
