"""Vmapped replica sweep (models/sweep.py): replica-0 bitwise parity with
the unbatched run, the fold_in tag-space contract, aggregate statistics,
and the support gates."""

import json

import jax
import numpy as np
import pytest

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import _LEADER_TAG, run
from cop5615_gossip_protocol_tpu.models.sweep import (
    MAX_REPLICAS,
    REPLICA_TAG0,
    SweepResult,
    replica_keys,
    run_replicas,
)
from cop5615_gossip_protocol_tpu.ops.faults import CRASH_TAG


def _unbatched_final(topo, cfg):
    cap = {}

    def hook(rounds, state):
        cap["state"] = jax.tree.map(np.asarray, state)
        cap["rounds"] = rounds

    res = run(topo, cfg, on_chunk=hook)
    return res, cap["state"]


def _assert_state_equal(a, b):
    for f in a._fields:
        np.testing.assert_array_equal(getattr(a, f), getattr(b, f),
                                      err_msg=f)


# --------------------------------------------------- replica-0 bitwise pin


def test_replica0_bitwise_gossip():
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=3,
                    chunk_rounds=7)
    topo = build_topology("full", 64, seed=3)
    res, final = _unbatched_final(topo, cfg)
    sweep = run_replicas(topo, cfg, 4)
    assert sweep.rounds[0] == res.rounds
    assert sweep.converged[0] == res.converged
    _assert_state_equal(sweep.final_states[0], final)
    # Replicas genuinely differ: not every replica repeats replica 0.
    assert len({tuple(s.count.tolist()) for s in sweep.final_states}) > 1


def test_replica0_bitwise_pushsum_stencil():
    cfg = SimConfig(n=48, topology="line", algorithm="push-sum", seed=0,
                    chunk_rounds=512, delivery="stencil")
    topo = build_topology("line", 48, seed=0)
    res, final = _unbatched_final(topo, cfg)
    sweep = run_replicas(topo, cfg, 3)
    assert sweep.rounds[0] == res.rounds
    _assert_state_equal(sweep.final_states[0], final)
    assert sweep.estimate_mae[0] == pytest.approx(res.estimate_mae)


def test_replica0_bitwise_crash_schedule():
    # The death plane is a pure function of cfg (PRNGKey(seed)+CRASH_TAG),
    # so all replicas share it — replica 0 must still replay the unbatched
    # faulted trajectory bitwise, quorum predicate included.
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=2,
                    chunk_rounds=8, crash_schedule="3:8", quorum=0.9,
                    max_rounds=4000)
    topo = build_topology("full", 64, seed=2)
    res, final = _unbatched_final(topo, cfg)
    sweep = run_replicas(topo, cfg, 3)
    assert sweep.rounds[0] == res.rounds
    _assert_state_equal(sweep.final_states[0], final)


# -------------------------------------------------------- fold_in tag space


def test_replica_tag_space_disjoint():
    # Base-key fold_in consumers: round indices (< 2**30), CRASH_TAG,
    # _LEADER_TAG. The replica tag range must collide with none of them.
    lo = REPLICA_TAG0 + 1
    hi = REPLICA_TAG0 + MAX_REPLICAS - 1
    assert lo >= 2**30  # above every round index
    assert not (lo <= CRASH_TAG <= hi)
    assert CRASH_TAG < lo  # CRASH_TAG sits below the replica region
    assert hi < _LEADER_TAG  # leader tag sits above it
    assert hi < 2**31  # int32 fold_in range


def test_replica_keys_distinct_and_replica0_is_base():
    base = jax.random.PRNGKey(7)
    keys = replica_keys(base, 8)
    data = [np.asarray(jax.random.key_data(k)) for k in keys]
    assert np.array_equal(data[0], np.asarray(jax.random.key_data(base)))
    as_tuples = {tuple(d.tolist()) for d in data}
    assert len(as_tuples) == 8  # no collisions


def test_replica_keys_bounds():
    base = jax.random.PRNGKey(0)
    with pytest.raises(ValueError):
        replica_keys(base, 0)
    with pytest.raises(ValueError):
        replica_keys(base, MAX_REPLICAS + 1)


# ------------------------------------------------------------- aggregates


def test_sweep_statistics_and_record():
    cfg = SimConfig(n=64, topology="full", algorithm="push-sum", seed=0,
                    chunk_rounds=64, delivery="pool")
    topo = build_topology("full", 64, seed=0)
    sweep = run_replicas(topo, cfg, 5)
    assert isinstance(sweep, SweepResult)
    assert len(sweep.rounds) == 5
    assert min(sweep.rounds) <= sweep.rounds_mean <= max(sweep.rounds)
    assert sweep.rounds_ci95 is not None and sweep.rounds_ci95 >= 0
    assert len(sweep.estimate_mae) == 5
    rec = sweep.to_record()
    assert "final_states" not in rec  # data, not a measurement
    assert rec["all_converged"] is True
    assert rec["wall_ms_per_replica"] == pytest.approx(rec["wall_ms"] / 5)
    json.dumps(rec)  # JSONL-ready


def test_sweep_single_replica_has_no_ci():
    cfg = SimConfig(n=64, topology="full", algorithm="gossip", seed=0)
    topo = build_topology("full", 64, seed=0)
    sweep = run_replicas(topo, cfg, 1)
    assert sweep.rounds_ci95 is None
    assert sweep.rounds_mean == sweep.rounds[0]


# ------------------------------------------------------------ support gates


def test_sweep_rejects_unsupported_configs():
    topo = build_topology("full", 64)
    with pytest.raises(ValueError, match="reference"):
        run_replicas(topo, SimConfig(n=64, semantics="reference"), 2)
    with pytest.raises(ValueError, match="fused"):
        run_replicas(topo, SimConfig(n=64, engine="fused"), 2)
    with pytest.raises(ValueError, match="n_devices"):
        run_replicas(topo, SimConfig(n=64, n_devices=4), 2)
    with pytest.raises(ValueError, match="stall"):
        run_replicas(topo, SimConfig(n=64, stall_chunks=2), 2)


def test_replicas_contracts_fail_fast_at_config_time():
    """ISSUE 6 satellite: --replicas + --engine fused used to raise only
    AFTER topology build (models/sweep._reject_unsupported); the contract
    now lives in SimConfig.__post_init__ — loud at construction, before
    any build work, same style as the revive/crash checks."""
    with pytest.raises(ValueError, match="fused"):
        SimConfig(n=64, engine="fused", replicas=2)
    with pytest.raises(ValueError, match="reference"):
        SimConfig(n=64, semantics="reference", replicas=2)
    with pytest.raises(ValueError, match="n_devices"):
        SimConfig(n=64, n_devices=4, replicas=2)
    with pytest.raises(ValueError, match="stall"):
        SimConfig(n=64, stall_chunks=2, replicas=2)
    with pytest.raises(ValueError, match="mass_tolerance|health sentinel"):
        SimConfig(n=64, algorithm="push-sum", mass_tolerance=1e-3,
                  replicas=2)
    with pytest.raises(ValueError, match="replicas must be"):
        SimConfig(n=64, replicas=0)
    with pytest.raises(ValueError, match="replicas must be"):
        SimConfig(n=64, replicas=MAX_REPLICAS + 1)
    # replicas=1 is the plain run: no sweep contract applies.
    SimConfig(n=64, engine="fused", replicas=1)


def test_cli_replicas_fused_fails_fast(capsys):
    """The CLI path: the error surfaces from SimConfig construction (exit
    2, before topology build), not from deep inside the sweep engine."""
    from cop5615_gossip_protocol_tpu.cli import main

    rc = main(["64", "full", "gossip", "--replicas", "2",
               "--engine", "fused"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "Invalid:" in err and "fused" in err


# ------------------------------------------------------------------- CLI


def test_cli_replicas_sweep(capsys):
    from cop5615_gossip_protocol_tpu.cli import main

    rc = main(["64", "full", "gossip", "--replicas", "3",
               "--chunk-rounds", "16"])
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["replicas"] == 3
    assert len(rec["rounds"]) == 3
    assert rec["all_converged"] is True
    assert rec["rounds_ci95"] is not None


def test_cli_replicas_rejects_checkpoint(capsys, tmp_path):
    from cop5615_gossip_protocol_tpu.cli import main

    rc = main(["64", "full", "gossip", "--replicas", "2",
               "--checkpoint", str(tmp_path / "ck.npz")])
    assert rc == 2
    assert "Invalid:" in capsys.readouterr().err
