"""CLI parity and the framework-only surfaces: reference-format output,
structured records, JSONL, checkpoint/resume, loud failure on bad input
(vs the reference's silent fall-through, program.fs:331)."""

import json

import pytest

from cop5615_gossip_protocol_tpu.cli import main


def test_cli_reference_parity_triple(capsys):
    rc = main(["64", "full", "gossip", "--quiet"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "-----------------------------------------------------------" in out
    assert "Convergence Time: " in out and " ms" in out


def test_cli_reference_spellings(capsys):
    rc = main(["25", "2D", "push-sum", "--semantics", "reference", "--dtype",
               "float64", "--max-rounds", "1000000"])
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["topology_kind"] == "ref2d"  # Q6: reference "2D" is a line
    assert rec["population"] == 26  # 5² + Q1 extra actor
    assert rec["config"]["semantics"] == "reference"


def test_cli_structured_record(capsys):
    rc = main(["64", "torus3d", "push-sum", "--dtype", "float64"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert rec["converged"] is True
    assert rec["rounds"] > 0 and rec["wall_ms"] > 0
    assert rec["resolved_delta"] == 1e-10
    assert rec["compile_s"] > 0  # compile split out of the timed run


def test_cli_invalid_inputs(capsys):
    assert main(["64", "moebius", "gossip"]) == 2
    assert "Invalid:" in capsys.readouterr().err
    assert main(["64", "full", "flood"]) == 2
    assert main(["-3", "full", "gossip"]) == 2


def test_cli_jsonl(tmp_path, capsys):
    p = tmp_path / "runs.jsonl"
    main(["64", "full", "gossip", "--quiet", "--jsonl", str(p)])
    main(["64", "full", "gossip", "--quiet", "--jsonl", str(p), "--seed", "1"])
    capsys.readouterr()
    lines = [json.loads(x) for x in p.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["config"]["seed"] == 0 and lines[1]["config"]["seed"] == 1


def test_cli_checkpoint_resume_is_stream_exact(tmp_path, capsys):
    # Full uninterrupted run.
    args = ["256", "grid2d", "push-sum", "--dtype", "float64", "--chunk-rounds", "200"]
    rc = main(args)
    full_rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    total_rounds = full_rec["rounds"]
    assert total_rounds > 400  # needs multiple chunks for the test to bite

    # Interrupted run: stop roughly halfway, checkpointing every chunk.
    ck = tmp_path / "state.npz"
    half = (total_rounds // 2 // 200) * 200
    rc = main(args + ["--max-rounds", str(half), "--checkpoint", str(ck)])
    capsys.readouterr()
    assert rc == 1  # not converged yet
    assert ck.exists()

    # Resume: must converge at exactly the uninterrupted round count —
    # round keys are derived from absolute round indices.
    rc = main(args + ["--resume", str(ck)])
    res_rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert res_rec["rounds"] == total_rounds
    assert res_rec["estimate_mae"] == pytest.approx(full_rec["estimate_mae"], rel=1e-9)


def test_cli_sharded_devices_flag(capsys):
    rc = main(["256", "full", "gossip", "--devices", "8", "--quiet"])
    assert rc == 0


def test_cli_resume_rejects_mismatched_flags(tmp_path, capsys):
    ck = tmp_path / "ck"  # suffix-less on purpose: save/load must normalize
    args = ["256", "grid2d", "push-sum", "--dtype", "float64", "--chunk-rounds", "200"]
    main(args + ["--max-rounds", "200", "--checkpoint", str(ck)])
    capsys.readouterr()
    assert (tmp_path / "ck.npz").exists()
    # Different seed → different random stream → must be refused loudly.
    rc = main(args + ["--resume", str(ck), "--seed", "5"])
    assert rc == 2
    assert "config mismatch" in capsys.readouterr().err
    # Matching flags (only loop knobs differ) → accepted.
    rc = main(args + ["--resume", str(ck)])
    assert rc == 0


def test_cli_reference_walk_cannot_be_sharded(capsys):
    rc = main(["64", "full", "push-sum", "--semantics", "reference",
               "--dtype", "float64", "--devices", "8"])
    assert rc == 2
    assert "cannot be sharded" in capsys.readouterr().err
