"""CLI parity and the framework-only surfaces: reference-format output,
structured records, JSONL, checkpoint/resume, loud failure on bad input
(vs the reference's silent fall-through, program.fs:331)."""

import json

import pytest

from cop5615_gossip_protocol_tpu.cli import main


def test_cli_reference_parity_triple(capsys):
    rc = main(["64", "full", "gossip", "--quiet"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "-----------------------------------------------------------" in out
    assert "Convergence Time: " in out and " ms" in out


def test_cli_reference_spellings(capsys):
    rc = main(["25", "2D", "push-sum", "--semantics", "reference", "--dtype",
               "float64", "--max-rounds", "1000000"])
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["topology_kind"] == "ref2d"  # Q6: reference "2D" is a line
    assert rec["population"] == 26  # 5² + Q1 extra actor
    assert rec["config"]["semantics"] == "reference"


def test_cli_structured_record(capsys):
    rc = main(["64", "torus3d", "push-sum", "--dtype", "float64"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert rec["converged"] is True
    assert rec["rounds"] > 0 and rec["wall_ms"] > 0
    assert rec["resolved_delta"] == 1e-10
    assert rec["compile_s"] > 0  # compile split out of the timed run


def test_cli_invalid_inputs(capsys):
    assert main(["64", "moebius", "gossip"]) == 2
    assert "Invalid:" in capsys.readouterr().err
    assert main(["64", "full", "flood"]) == 2
    assert main(["-3", "full", "gossip"]) == 2


def test_cli_jsonl(tmp_path, capsys):
    p = tmp_path / "runs.jsonl"
    main(["64", "full", "gossip", "--quiet", "--jsonl", str(p)])
    main(["64", "full", "gossip", "--quiet", "--jsonl", str(p), "--seed", "1"])
    capsys.readouterr()
    lines = [json.loads(x) for x in p.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["config"]["seed"] == 0 and lines[1]["config"]["seed"] == 1


def test_cli_checkpoint_resume_is_stream_exact(tmp_path, capsys):
    # Full uninterrupted run.
    args = ["256", "grid2d", "push-sum", "--dtype", "float64", "--chunk-rounds", "200"]
    rc = main(args)
    full_rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    total_rounds = full_rec["rounds"]
    assert total_rounds > 400  # needs multiple chunks for the test to bite

    # Interrupted run: stop roughly halfway, checkpointing every chunk.
    ck = tmp_path / "state.npz"
    half = (total_rounds // 2 // 200) * 200
    rc = main(args + ["--max-rounds", str(half), "--checkpoint", str(ck)])
    capsys.readouterr()
    assert rc == 1  # not converged yet
    assert ck.exists()

    # Resume: must converge at exactly the uninterrupted round count —
    # round keys are derived from absolute round indices.
    rc = main(args + ["--resume", str(ck)])
    res_rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert res_rec["rounds"] == total_rounds
    assert res_rec["estimate_mae"] == pytest.approx(full_rec["estimate_mae"], rel=1e-9)


def test_cli_resume_auto_restart_workflow(tmp_path, capsys):
    # The crash-only-restarts workflow: the SAME command line runs fresh
    # when no sidecar exists, and picks up from the last auto-checkpoint
    # when one does — landing on the uninterrupted trajectory exactly.
    ck = tmp_path / "auto.npz"
    args = ["256", "grid2d", "push-sum", "--dtype", "float64",
            "--chunk-rounds", "200", "--checkpoint", str(ck),
            "--resume", "auto"]
    # Uninterrupted oracle (no checkpointing, no resume).
    rc = main(["256", "grid2d", "push-sum", "--dtype", "float64",
               "--chunk-rounds", "200"])
    full_rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    total_rounds = full_rec["rounds"]
    half = (total_rounds // 2 // 200) * 200
    # First launch: sidecar absent -> fresh start; "killed" at half.
    rc = main(args + ["--max-rounds", str(half)])
    capsys.readouterr()
    assert rc == 1 and ck.exists()
    # Relaunch of the identical command: resumes from the sidecar.
    rc = main(args)
    res_rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert res_rec["rounds"] == total_rounds
    # --resume auto without --checkpoint is a loud config error.
    rc = main(["64", "full", "gossip", "--resume", "auto"])
    assert rc == 2
    assert "--resume auto" in capsys.readouterr().err


def test_cli_trace_resume_seeds_newly_converged(tmp_path, capsys):
    # ADVICE r2: resuming with --trace-convergence must seed the baseline
    # from the checkpoint - nodes converged before the checkpoint are not
    # "newly converged" in the resumed run's first trace record.
    args = ["400", "line", "gossip", "--chunk-rounds", "64"]
    rc = main(args)
    full_rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    # Stop at a point where some nodes have already converged.
    half = (full_rec["rounds"] // 2 // 64) * 64
    ck = tmp_path / "state.npz"
    rc = main(args + ["--max-rounds", str(half), "--checkpoint", str(ck)])
    capsys.readouterr()
    assert ck.exists()
    import numpy as np
    pre_conv = int(np.load(ck)["conv"].sum())
    assert pre_conv > 0, "pick a config where some nodes converge by half"
    tr = tmp_path / "trace.jsonl"
    rc = main(args + ["--resume", str(ck), "--trace-convergence", str(tr)])
    capsys.readouterr()
    assert rc == 0
    recs = [json.loads(x) for x in tr.read_text().splitlines()]
    # Each record's newly_converged must be the true per-chunk increment:
    # the first one counts from the checkpoint's converged set, not from 0.
    assert recs[0]["newly_converged"] == recs[0]["converged_count"] - pre_conv
    assert sum(r["newly_converged"] for r in recs) == recs[-1]["converged_count"] - pre_conv


def test_cli_sharded_devices_flag(capsys):
    rc = main(["256", "full", "gossip", "--devices", "8", "--quiet"])
    assert rc == 0


def test_cli_resume_rejects_mismatched_flags(tmp_path, capsys):
    ck = tmp_path / "ck"  # suffix-less on purpose: save/load must normalize
    args = ["256", "grid2d", "push-sum", "--dtype", "float64", "--chunk-rounds", "200"]
    main(args + ["--max-rounds", "200", "--checkpoint", str(ck)])
    capsys.readouterr()
    assert (tmp_path / "ck.npz").exists()
    # Different seed → different random stream → must be refused loudly.
    rc = main(args + ["--resume", str(ck), "--seed", "5"])
    assert rc == 2
    assert "config mismatch" in capsys.readouterr().err
    # Matching flags (only loop knobs differ) → accepted.
    rc = main(args + ["--resume", str(ck)])
    assert rc == 0


def test_cli_reference_walk_cannot_be_sharded(capsys):
    rc = main(["64", "full", "push-sum", "--semantics", "reference",
               "--dtype", "float64", "--devices", "8"])
    assert rc == 2
    assert "cannot be sharded" in capsys.readouterr().err


def test_checkpoint_rejects_mismatched_stream_version(tmp_path):
    # A pool-delivery checkpoint written under a different random-stream
    # derivation (the pre-packed-choice scheme) must be refused, not silently
    # resumed onto a different trajectory. Non-pool checkpoints are
    # unaffected by the v1->v2 change and must keep loading.
    import jax.numpy as jnp
    import numpy as np

    from cop5615_gossip_protocol_tpu import SimConfig
    from cop5615_gossip_protocol_tpu.models.pushsum import PushSumState
    from cop5615_gossip_protocol_tpu.utils import checkpoint as ckpt

    st = PushSumState(
        s=jnp.arange(16, dtype=jnp.float32), w=jnp.ones((16,), jnp.float32),
        term=jnp.zeros((16,), jnp.int32), conv=jnp.zeros((16,), bool),
    )
    cfg_pool = SimConfig(n=16, topology="full", algorithm="push-sum",
                         delivery="pool")
    p = tmp_path / "ck.npz"
    ckpt.save(p, st, 32, cfg_pool)
    # Round-trips at the current version.
    _, rounds, _ = ckpt.load(p)
    assert rounds == 32

    def rewrite_stream(version):
        with np.load(p) as z:
            data = {k: z[k] for k in z.files}
        if version is None:
            del data["__stream__"]
        else:
            data["__stream__"] = np.int64(version)
        np.savez_compressed(p, **data)
        # Re-bless the integrity digests (ISSUE 19) so the stream-version
        # rule is what fires, not the corrupt-archive refusal.
        ckpt._refresh_digests(p)

    rewrite_stream(1)
    with pytest.raises(ValueError, match="stream version"):
        ckpt.load(p)

    # Pre-versioning checkpoints (no marker at all) are treated as stream 1.
    rewrite_stream(None)
    with pytest.raises(ValueError, match="stream version"):
        ckpt.load(p)

    # A scatter-delivery run never consumed the pool-choice stream: a
    # version-1 checkpoint of it replays bitwise-identically and must load.
    cfg_scatter = SimConfig(n=16, topology="full", algorithm="push-sum")
    ckpt.save(p, st, 32, cfg_scatter)
    rewrite_stream(1)
    _, rounds, _ = ckpt.load(p)
    assert rounds == 32

    # v2 -> v3 changed only the fault-gate derivation: a fault-free pool
    # checkpoint from v2 never consumed it and must keep loading...
    ckpt.save(p, st, 32, cfg_pool)
    rewrite_stream(2)
    _, rounds, _ = ckpt.load(p)
    assert rounds == 32
    # ...while a drop-gated run consumed the changed stream and is refused,
    # as is any checkpoint from a NEWER stream than this build understands.
    cfg_gate = SimConfig(n=16, topology="full", algorithm="push-sum",
                         delivery="pool", fault_rate=0.25)
    ckpt.save(p, st, 32, cfg_gate)
    rewrite_stream(2)
    with pytest.raises(ValueError, match="stream version"):
        ckpt.load(p)
    ckpt.save(p, st, 32, cfg_pool)
    rewrite_stream(99)
    with pytest.raises(ValueError, match="stream version"):
        ckpt.load(p)


def test_cli_checkpoint_resume_across_device_counts(tmp_path, capsys):
    # Checkpoints hold exactly n entries (the sharded runner's device padding
    # is stripped on save), so a run checkpointed under one mesh size resumes
    # under another — or single-device. Gossip integer state + device-count-
    # invariant stream => identical total rounds everywhere. n=1001 makes the
    # 8-device padding (1008) visible if it ever leaks into the file.
    args = ["1001", "full", "gossip", "--chunk-rounds", "16"]
    rc = main(args + ["--devices", "8"])
    full_rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0

    ck = tmp_path / "ck.npz"
    rc = main(args + ["--devices", "8", "--max-rounds", "16",
                      "--checkpoint", str(ck)])
    capsys.readouterr()
    assert rc == 1 and ck.exists()

    import numpy as np
    with np.load(ck) as z:
        assert z["count"].shape == (1001,)  # padding stripped

    for extra in (["--devices", "4"], []):  # different mesh, single device
        rc = main(args + extra + ["--resume", str(ck)])
        rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 0, extra
        assert rec["rounds"] == full_rec["rounds"], extra
        assert rec["converged_count"] == full_rec["converged_count"], extra


def test_cli_coordinator_flag_validation(capsys):
    rc = main(["64", "full", "gossip", "--coordinator", "127.0.0.1:1"])
    assert rc == 2
    assert "--num-processes" in capsys.readouterr().err
    rc = main(["64", "full", "gossip", "--devices", "8", "--coordinator",
               "127.0.0.1:1", "--num-processes", "3", "--process-id", "0"])
    assert rc == 2
    assert "divisible" in capsys.readouterr().err


def test_cli_backend_refsim(capsys):
    # The north-star `--backend {akka|jax}` switch (BASELINE.json): the
    # native DES stands in for the Akka runtime on the same parity triple.
    rc = main(["100", "2D", "gossip", "--backend", "refsim"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "-----------------------------------------------------------" in out
    assert "Convergence Time: " in out
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["backend"] == "refsim"
    assert rec["config"]["topology"] == "ref2d"  # Q6 applies: "2D" is a line
    assert rec["population"] == rec["target_count"] + 1  # Q1
    assert rec["converged"] is True
    assert rec["events"] > 0


def test_cli_backend_akka_alias_and_seed(capsys):
    rc1 = main(["50", "full", "push-sum", "--backend", "akka", "--seed", "7"])
    rec1 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    rc2 = main(["50", "full", "push-sum", "--backend", "refsim", "--seed", "7"])
    rec2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc1 == rc2 == 0
    # Same DES, same seed — identical event trajectory either spelling.
    assert rec1["events"] == rec2["events"]
    assert rec1["leader"] == rec2["leader"]
    assert rec1["max_queue"] == rec2["max_queue"] == 1  # single-walk push-sum


def test_cli_backend_refsim_rejects_framework_topologies(capsys):
    rc = main(["100", "torus3d", "gossip", "--backend", "refsim"])
    err = capsys.readouterr().err
    assert rc == 2
    assert "not one the reference implements" in err


def test_cli_backend_refsim_rejects_jax_only_flags(capsys):
    rc = main(["100", "full", "gossip", "--backend", "refsim", "--devices", "4"])
    err = capsys.readouterr().err
    assert rc == 2
    assert "--devices" in err and "does not apply" in err
    rc = main(["100", "full", "gossip", "--backend", "akka",
               "--engine", "fused"])
    err = capsys.readouterr().err
    assert rc == 2
    assert "--engine" in err


def test_resume_from_converged_state_runs_zero_rounds(tmp_path):
    # A checkpoint taken at (or after) convergence must resume to an
    # immediate no-op on every engine: the loop predicate seeds from the
    # resumed conv vector, matching the fused kernels' conv-plane seeding.
    from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh
    from cop5615_gossip_protocol_tpu.parallel.sharded import run_sharded

    cfg = SimConfig(n=256, topology="grid2d", algorithm="gossip")
    topo = build_topology("grid2d", 256)
    full = run(topo, cfg)
    assert full.converged

    final_state = {}

    def grab(rounds, st):
        final_state["st"], final_state["rounds"] = st, rounds

    run(topo, cfg, on_chunk=grab)
    resumed = run(
        topo, cfg,
        start_state=final_state["st"], start_round=final_state["rounds"],
    )
    assert resumed.converged
    assert resumed.rounds == final_state["rounds"]  # zero extra rounds

    mesh = make_mesh(4)
    import numpy as np

    unpadded = type(final_state["st"])(
        *(np.asarray(x)[: topo.n] for x in final_state["st"])
    )
    resumed_sh = run_sharded(
        topo, cfg, mesh=mesh,
        start_state=unpadded, start_round=final_state["rounds"],
    )
    assert resumed_sh.converged
    assert resumed_sh.rounds == final_state["rounds"]


def test_cli_trace_convergence(tmp_path, capsys):
    # SURVEY §5 metrics plan: per-round counters behind a flag, sampled at
    # chunk boundaries (each sample is a device->host sync).
    tr = tmp_path / "trace.jsonl"
    rc = main(["256", "grid2d", "gossip", "--quiet", "--chunk-rounds", "32",
               "--trace-convergence", str(tr)])
    capsys.readouterr()
    assert rc == 0
    recs = [json.loads(x) for x in tr.read_text().splitlines()]
    assert len(recs) >= 2  # multiple chunks sampled
    convs = [r["converged_count"] for r in recs]
    assert convs == sorted(convs)  # monotone
    assert convs[-1] == 256
    assert sum(r["newly_converged"] for r in recs) == 256
    actives = [r["active_count"] for r in recs]
    assert actives == sorted(actives)  # rumor spread is monotone too

    tr2 = tmp_path / "trace2.jsonl"
    rc = main(["256", "grid2d", "push-sum", "--quiet", "--chunk-rounds", "512",
               "--trace-convergence", str(tr2), "--dtype", "float64"])
    capsys.readouterr()
    assert rc == 0
    recs = [json.loads(x) for x in tr2.read_text().splitlines()]
    assert recs[-1]["converged_count"] == 256
    assert recs[-1]["estimate_mae"] < 1.0

    # Composes with checkpointing (both hooks fire at the same boundaries).
    tr3 = tmp_path / "trace3.jsonl"
    ck = tmp_path / "ck.npz"
    rc = main(["256", "grid2d", "gossip", "--quiet", "--chunk-rounds", "32",
               "--trace-convergence", str(tr3), "--checkpoint", str(ck)])
    capsys.readouterr()
    assert rc == 0
    assert ck.exists() and tr3.read_text().strip()
