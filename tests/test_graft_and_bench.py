"""The driver contracts: entry() compiles single-chip, dryrun_multichip runs
the full sharded step on a virtual mesh, bench.py emits one valid JSON line."""

import json
import subprocess
import sys
from pathlib import Path

import jax
import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

import __graft_entry__  # noqa: E402


def test_entry_jits_and_steps():
    fn, example_args = __graft_entry__.entry()
    out = jax.jit(fn)(*example_args)
    state0 = example_args[0]
    assert out.s.shape == state0.s.shape
    # One round conserves mass.
    assert float(out.s.sum()) == float(state0.s.sum())


@pytest.mark.slow  # 8-virtual-device sweep across every sharded tier; ~3-4 min on CPU
def test_dryrun_multichip():
    __graft_entry__.dryrun_multichip(8)


def test_bench_emits_one_json_line():
    # Subprocess so bench's own platform handling is exercised; tiny n keeps
    # it fast, CPU keeps it off the shared TPU tunnel.
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--n", "2048", "--platform", "cpu"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline"}
    assert rec["unit"] == "rounds/sec"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0
