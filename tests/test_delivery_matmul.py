"""The MXU matmul delivery tier (ISSUE 12).

delivery='matmul' is the pool tier's sampling stream (identical per-round
choices/offsets) with delivery recast onto the MXU: the chunked engine
delivers by blocked one-hot dot_general (ops/delivery.deliver_matmul),
the fused pool kernels execute the lane-rotation blend as 128x128 one-hot
tiles (ops/fused_pool._lane_blend_mm). Oracles:

- op-level: the one-hot delivery equals scatter-add and the pool masked
  rolls over identical targets (int channels exact, floats to summation
  order); the in-kernel lane blend is BITWISE the roll blend; the
  full-topology closed form and the CSR blocked SpMV match brute force;
- engine-level: gossip trajectories are bitwise the chunked pool path
  across full/imp kinds at two sizes (integer-exact sums); push-sum
  conserves mass to <= 1 ulp at float64 with dual-oracle rounds AND
  converged-set parity, float32/bfloat16 hold the documented quality
  envelopes (tests/test_bfloat16.py bounds);
- the resolved policy: structured refusals off the supported kinds and
  engines (the analysis lint checks the runner-ladder wording), and the
  serving keys place a matmul-tier request in its own bucket.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import delivery, sampling
from cop5615_gossip_protocol_tpu.serving import keys as keys_mod


def _pool_targets(seed, rnd, n, K):
    kr = sampling.round_key(jax.random.PRNGKey(seed), rnd)
    offs = sampling.pool_offsets(kr, K, n)
    choice = sampling.pool_choice_packed(kr, n, K)
    ids = jnp.arange(n, dtype=jnp.int32)
    return sampling.targets_pool(choice, offs, ids, n), choice, offs


# --- op level ---------------------------------------------------------------


@pytest.mark.parametrize("n", [37, 1000])  # 37: padded-tail/modulo edge;
# 1000: multi-block — the 256 mid-size rides the engine-level pins below
def test_deliver_matmul_matches_scatter_and_rolls(n):
    targets, choice, offs = _pool_targets(1, 5, n, 4)
    vals_i = jnp.arange(n, dtype=jnp.int32) % 7 + 1
    vals_f = jnp.linspace(0.5, 2.0, n, dtype=jnp.float32)
    inbox = delivery.deliver_matmul(
        jnp.stack([vals_i.astype(jnp.float32), vals_f]), targets, n
    )
    want_i = delivery.deliver(vals_i, targets, n)
    want_f = delivery.deliver(vals_f, targets, n)
    # Integer-valued f32 channels: every partial sum is an exact integer
    # in the accumulator — bitwise the scatter path.
    assert (np.asarray(inbox[0]) == np.asarray(want_i)).all()
    np.testing.assert_allclose(
        np.asarray(inbox[1]), np.asarray(want_f), rtol=1e-6
    )
    roll_i = delivery.deliver_pool(
        jnp.stack([vals_i.astype(jnp.float32)]), choice, offs
    )[0]
    assert (np.asarray(inbox[0]) == np.asarray(roll_i)).all()
    # 1-D input form
    one = delivery.deliver_matmul(vals_i.astype(jnp.float32), targets, n)
    assert (np.asarray(one) == np.asarray(want_i)).all()


def test_deliver_matmul_float64_accumulates_exactly_for_ints():
    n = 512
    targets, _, _ = _pool_targets(2, 0, n, 8)
    vals = jnp.arange(n, dtype=jnp.float64)
    inbox = delivery.deliver_matmul(vals, targets, n)
    assert inbox.dtype == jnp.float64
    want = delivery.deliver(vals, targets, n)
    assert (np.asarray(inbox) == np.asarray(want)).all()


def test_lane_blend_mm_bitwise_matches_roll_blend():
    # The fused kernels' building block: one pair of 128x128 one-hot MXU
    # tiles must reproduce the roll/select blend bit for bit (each output
    # lane selects exactly one input value), for float and int planes.
    from cop5615_gossip_protocol_tpu.ops.fused_pool import (
        LANES,
        _lane_blend_mm,
    )

    rng = np.random.default_rng(3)
    lane = jax.lax.broadcasted_iota(jnp.int32, (64, LANES), 1)
    for r in (0, 1, 17, 127):
        pa = jnp.asarray(rng.standard_normal((64, LANES)).astype(np.float32))
        pb = jnp.asarray(rng.standard_normal((64, LANES)).astype(np.float32))
        want = jnp.where(
            lane >= r, jnp.roll(pa, r, axis=1), jnp.roll(pb, r, axis=1)
        )
        got = _lane_blend_mm(pa, pb, jnp.int32(r))
        assert (np.asarray(got) == np.asarray(want)).all(), f"f32 r={r}"
        pai = jnp.asarray(rng.integers(-1, 16, (64, LANES)).astype(np.int32))
        pbi = jnp.asarray(rng.integers(-1, 16, (64, LANES)).astype(np.int32))
        wanti = jnp.where(
            lane >= r, jnp.roll(pai, r, axis=1), jnp.roll(pbi, r, axis=1)
        )
        goti = _lane_blend_mm(pai, pbi, jnp.int32(r))
        assert goti.dtype == jnp.int32
        assert (np.asarray(goti) == np.asarray(wanti)).all(), f"i32 r={r}"


def test_aggregate_full_closed_form():
    # J - I adjacency product without materializing N^2.
    n = 200
    vals = jnp.linspace(-1.0, 3.0, n, dtype=jnp.float32)
    got = np.asarray(delivery.aggregate_full(vals))
    A = np.ones((n, n), np.float32) - np.eye(n, dtype=np.float32)
    np.testing.assert_allclose(got, A.T @ np.asarray(vals), rtol=1e-5)
    stacked = np.asarray(delivery.aggregate_full(jnp.stack([vals, vals * 2])))
    np.testing.assert_allclose(stacked[1], A.T @ (2 * np.asarray(vals)),
                               rtol=1e-5)


def test_spmv_blocked_matches_brute_force():
    # CSR in-edge groundwork (ROADMAP item 3 scale-free graphs): the BSR
    # tiles + batched dot_general must equal a per-edge accumulate,
    # including multi-edges.
    rng = np.random.default_rng(0)
    n = 300
    indptr = [0]
    indices: list = []
    for _ in range(n):
        deg = int(rng.integers(1, 6))
        indices.extend(rng.integers(0, n, deg).tolist())
        indptr.append(len(indices))
    plan = delivery.build_spmv_plan(np.array(indptr), np.array(indices), n)
    vals = jnp.arange(n, dtype=jnp.float32)
    got = np.asarray(delivery.deliver_spmv(vals, plan))
    want = np.zeros(n, np.float64)
    for j in range(n):
        for i in indices[indptr[j]:indptr[j + 1]]:
            want[j] += float(i)
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=1e-6)


# --- engine level: gossip bitwise across full/pool kinds --------------------


def _states_and_result(cfg, topo):
    grab = {}
    r = run(topo, cfg, on_chunk=lambda rounds, s: grab.update(state=s))
    return r, grab["state"]


@pytest.mark.parametrize("n", [256, 1000])
def test_matmul_gossip_full_bitwise_vs_chunked_pool(n):
    results = {}
    for d in ("pool", "matmul"):
        cfg = SimConfig(n=n, topology="full", algorithm="gossip",
                        delivery=d, max_rounds=5000)
        results[d] = _states_and_result(cfg, build_topology("full", n))
    (ra, sa), (rb, sb) = results["pool"], results["matmul"]
    assert ra.converged and rb.converged
    assert ra.rounds == rb.rounds
    for f in ("count", "active", "conv"):
        assert (np.asarray(getattr(sa, f)) == np.asarray(getattr(sb, f))).all(), f


@pytest.mark.parametrize("kind,n", [("imp3d", 512), ("imp2d", 256)])
def test_matmul_gossip_imp_bitwise_vs_chunked_pool(kind, n):
    results = {}
    for d in ("pool", "matmul"):
        cfg = SimConfig(n=n, topology=kind, algorithm="gossip",
                        delivery=d, max_rounds=5000)
        results[d] = _states_and_result(cfg, build_topology(kind, n))
    (ra, sa), (rb, sb) = results["pool"], results["matmul"]
    assert ra.converged and rb.converged
    assert ra.rounds == rb.rounds
    for f in ("count", "active", "conv"):
        assert (np.asarray(getattr(sa, f)) == np.asarray(getattr(sb, f))).all(), f


@pytest.mark.slow  # tier-1 budget: the fault-free pins above already pin
# the stream; the gate interaction rides the slow oracle set
def test_matmul_gossip_drop_gate_bitwise():
    # The failure-model gate rides the same stream: drop-gated rounds must
    # stay bitwise across the two delivery mechanisms.
    n = 512
    results = {}
    for d in ("pool", "matmul"):
        cfg = SimConfig(n=n, topology="full", algorithm="gossip",
                        delivery=d, fault_rate=0.3, max_rounds=8000)
        results[d] = _states_and_result(cfg, build_topology("full", n))
    (ra, sa), (rb, sb) = results["pool"], results["matmul"]
    assert ra.rounds == rb.rounds
    for f in ("count", "active", "conv"):
        assert (np.asarray(getattr(sa, f)) == np.asarray(getattr(sb, f))).all(), f


# --- push-sum: mass to ulp + dual oracle + dtype envelopes ------------------


def test_matmul_pushsum_f64_mass_to_ulp_and_dual_oracle():
    # ISSUE 12 acceptance: push-sum reassociates under the matmul sum
    # order, so the pins are (a) mass conservation to <= 1 ulp of the
    # initial totals at float64 and (b) dual-oracle parity — the matmul
    # run and the chunked pool run agree on rounds AND the converged set.
    n = 1024
    caps = {}
    res = {}
    for d in ("pool", "matmul"):
        cfg = SimConfig(n=n, topology="full", algorithm="push-sum",
                        delivery=d, dtype="float64", max_rounds=8000)
        res[d], caps[d] = _states_and_result(cfg, build_topology("full", n))
    assert res["pool"].converged and res["matmul"].converged
    assert res["pool"].rounds == res["matmul"].rounds
    assert (
        np.asarray(caps["pool"].conv) == np.asarray(caps["matmul"].conv)
    ).all(), "converged-set parity"
    st = caps["matmul"]
    s0, w0 = n * (n - 1) / 2.0, float(n)
    assert abs(np.asarray(st.s, np.float64).sum() - s0) <= np.spacing(s0)
    assert abs(np.asarray(st.w, np.float64).sum() - w0) <= np.spacing(w0)


@pytest.mark.slow  # tier-1 budget: f32 quality is bracketed by the fast
# f64 dual-oracle (exact) and bf16 (coarse) pins
def test_matmul_pushsum_f32_quality():
    n = 1024
    cfg = SimConfig(n=n, topology="full", algorithm="push-sum",
                    delivery="matmul", max_rounds=8000)
    r = run(build_topology("full", n), cfg)
    assert r.converged and r.converged_count == n
    assert r.estimate_mae < 1e-2


def test_matmul_pushsum_bf16_upcast_quality():
    # The bf16 path upcasts the contraction to f32 accumulation
    # (ops/delivery._acc_dtype via preferred_element_type) and must hold
    # tests/test_bfloat16.py's expander-class envelope: <0.5% rel MAE on
    # full.
    n = 1024
    cfg = SimConfig(n=n, topology="full", algorithm="push-sum",
                    delivery="matmul", dtype="bfloat16", max_rounds=8000)
    r = run(build_topology("full", n), cfg)
    assert r.converged
    rel = r.estimate_mae / r.true_mean
    assert rel < 0.005, f"bf16 matmul estimate degraded: rel MAE {rel:.4%}"


# --- fused tier (interpret mode — slow suite) -------------------------------


@pytest.mark.slow  # interpret-mode run pair; see tier-1 budget note in test_fused.py
@pytest.mark.parametrize("n", [1000, 16384])  # the chunked one-hot leg is
# n^2-class work on CPU (no MXU), so the slow pair stays mid-sized
def test_fused_pool_matmul_gossip_bitwise(n):
    # The VMEM pool kernel with the one-hot MXU lane blend vs the chunked
    # matmul round: gossip integer trajectories identical.
    results = {}
    for engine in ("chunked", "fused"):
        cfg = SimConfig(n=n, topology="full", algorithm="gossip",
                        delivery="matmul", engine=engine,
                        max_rounds=60000, chunk_rounds=32)
        results[engine] = run(build_topology("full", n), cfg)
    a, b = results["chunked"], results["fused"]
    assert a.converged and b.converged
    assert a.rounds == b.rounds
    assert a.converged_count == b.converged_count


@pytest.mark.slow  # interpret-mode Pallas pair on the 2-device mesh
def test_pool2_sharded_matmul_bitwise_vs_chunked():
    # The replicated-pool2 composition with the per-shard one-hot blend
    # after its one all_gather: bitwise the chunked pool path (and hence
    # the chunked matmul path) for gossip; its WIRE_SPEC is unchanged —
    # the static auditor proves that (analysis matrix matmul rows).
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh
    from cop5615_gossip_protocol_tpu.parallel.pool2_sharded import (
        run_pool2_sharded,
    )

    n, rounds = 65536, 8
    topo = build_topology("full", n)
    grab = {}
    r1 = run(
        topo,
        SimConfig(n=n, topology="full", algorithm="gossip",
                  delivery="matmul", engine="chunked",
                  max_rounds=rounds, chunk_rounds=rounds),
        on_chunk=lambda r, s: grab.update(a=s),
    )
    r2 = run_pool2_sharded(
        topo,
        SimConfig(n=n, topology="full", algorithm="gossip",
                  delivery="matmul", engine="fused", n_devices=2,
                  chunk_rounds=1, max_rounds=rounds),
        mesh=make_mesh(2), on_chunk=lambda r, s: grab.update(b=s),
    )
    assert r1.rounds == r2.rounds == rounds
    for f in ("count", "active", "conv"):
        a = np.asarray(getattr(grab["a"], f))
        b = np.asarray(getattr(grab["b"], f))[:n]
        assert (a == b).all(), f


# --- resolved policy: refusals + serving keys -------------------------------


def test_matmul_config_rejected_off_pool_kinds():
    with pytest.raises(ValueError, match="matmul"):
        SimConfig(n=100, topology="line", delivery="matmul")
    with pytest.raises(ValueError, match="matmul"):
        SimConfig(n=100, topology="torus3d", delivery="matmul")
    with pytest.raises(ValueError, match="power of two"):
        SimConfig(n=100, topology="full", delivery="matmul", pool_size=6)


def test_matmul_refused_on_sharded_xla_engine():
    cfg = SimConfig(n=1024, topology="full", algorithm="gossip",
                    delivery="matmul", n_devices=8, engine="chunked",
                    max_rounds=100)
    with pytest.raises(ValueError, match="composition"):
        run(build_topology("full", 1024), cfg)


def test_matmul_fused_refused_on_imp_kinds():
    # engine='auto' demotes imp matmul to the chunked engine (covered by
    # the bitwise tests above); an explicit engine='fused' fails loudly.
    cfg = SimConfig(n=512, topology="imp3d", algorithm="gossip",
                    delivery="matmul", engine="fused", max_rounds=100)
    with pytest.raises(ValueError, match="chunked"):
        run(build_topology("imp3d", 512), cfg)


def test_matmul_dup_delay_rejected():
    cfg = SimConfig(n=256, topology="full", algorithm="gossip",
                    delivery="matmul", dup_rate=0.1, max_rounds=100)
    with pytest.raises(ValueError, match="dup/delay"):
        run(build_topology("full", 256), cfg)


def test_matmul_checkpoint_stream_guard(tmp_path):
    # The matmul tier consumes the identical packed pool-choice stream as
    # the pool tier, so a checkpoint written under the pre-packed-choice
    # derivation (stream v1 / unversioned) must be REFUSED on resume —
    # the same guard delivery='pool' gets (utils/checkpoint.load).
    from cop5615_gossip_protocol_tpu.models.pushsum import PushSumState
    from cop5615_gossip_protocol_tpu.utils import checkpoint as ckpt

    st = PushSumState(
        s=jnp.arange(16, dtype=jnp.float32), w=jnp.ones((16,), jnp.float32),
        term=jnp.zeros((16,), jnp.int32), conv=jnp.zeros((16,), bool),
    )
    cfg = SimConfig(n=16, topology="full", algorithm="push-sum",
                    delivery="matmul")
    p = tmp_path / "ck.npz"
    ckpt.save(p, st, 32, cfg)
    _, rounds, _ = ckpt.load(p)  # current version round-trips
    assert rounds == 32
    with np.load(p) as z:
        data = {k: z[k] for k in z.files}
    data["__stream__"] = np.int64(1)
    np.savez_compressed(p, **data)
    # Re-bless the integrity digests (ISSUE 19) so the stream-version
    # rule is what fires, not the corrupt-archive refusal.
    ckpt._refresh_digests(p)
    with pytest.raises(ValueError, match="stream version"):
        ckpt.load(p)


def test_matmul_lands_in_its_own_serving_bucket():
    # Resolved-policy round-trip through serving/keys.py: the matmul tier
    # traces a different chunk program than the pool tier (and pins
    # pool_size like it), so the canonical engine key, the batcher bucket
    # key, and the /stats label must all separate.
    topo = build_topology("full", 1024)
    cfg_pool = SimConfig(n=1024, topology="full", delivery="pool")
    cfg_mm = SimConfig(n=1024, topology="full", delivery="matmul")
    assert keys_mod.canonical_key(cfg_pool, topo) != keys_mod.canonical_key(
        cfg_mm, topo
    )
    assert keys_mod.serve_bucket_key(cfg_pool, topo) != (
        keys_mod.serve_bucket_key(cfg_mm, topo)
    )
    # pool_size is part of the matmul compile class (same stream contract
    # as the pool tier).
    cfg_mm8 = SimConfig(n=1024, topology="full", delivery="matmul",
                        pool_size=8)
    assert keys_mod.canonical_key(cfg_mm, topo) != keys_mod.canonical_key(
        cfg_mm8, topo
    )
    # ... and two identical matmul requests share one bucket (warm-pool
    # reuse, not a per-request retrace).
    assert keys_mod.canonical_key(cfg_mm, topo) == keys_mod.canonical_key(
        SimConfig(n=1024, topology="full", delivery="matmul"), topo
    )
    assert keys_mod.bucket_label(cfg_mm, topo).startswith("gossip/full/")
