"""Topology builders checked against closed-form adjacency (SURVEY.md §4):
degree counts, symmetry, rounding rules (C3), and the reference quirks
Q1/Q6/Q8/Q9 in reference-semantics mode."""

import math

import numpy as np
import pytest

from cop5615_gossip_protocol_tpu.ops import topology as T


def dense_adj(topo):
    a = np.zeros((topo.n, topo.n), dtype=bool)
    for i in range(topo.n):
        for k in range(topo.degree[i]):
            a[i, topo.neighbors[i, k]] = True
    return a


def test_line_degrees_and_symmetry():
    t = T.build_line(10)
    assert t.n == 10 and t.target_count == 10
    assert t.degree[0] == 1 and t.degree[-1] == 1
    assert (t.degree[1:-1] == 2).all()
    a = dense_adj(t)
    assert (a == a.T).all()
    # node i ↔ i+1 chain exactly
    assert all(a[i, i + 1] for i in range(9))
    assert a.sum() == 2 * 9


def test_line_reference_population_q1():
    # Q1: n+1 actors spawned, convergence target n (program.fs:152-154, 178).
    t = T.build_line(10, reference=True)
    assert t.n == 11 and t.target_count == 10


def test_ring_regular():
    t = T.build_ring(8)
    assert (t.degree == 2).all()
    a = dense_adj(t)
    assert (a == a.T).all() and a.sum() == 16


def test_full_implicit():
    t = T.build_full(100)
    assert t.implicit and t.neighbors is None
    assert t.n == 100 and t.target_count == 100
    t_ref = T.build_full(100, reference=True)
    assert t_ref.n == 101 and t_ref.target_count == 100


def test_grid2d_rounding_and_degrees():
    # n rounds UP to the next perfect square (program.fs:228-229).
    t = T.build_grid2d(10)
    assert t.n == 16
    deg = np.asarray(t.degree)
    # 4 corners of degree 2, 8 edge nodes of degree 3, 4 interior of degree 4
    assert sorted(deg.tolist()).count(2) == 4
    assert (deg == 3).sum() == 8
    assert (deg == 4).sum() == 4
    a = dense_adj(t)
    assert (a == a.T).all()
    # coordinate round-trip: neighbor indices differ by ±1 or ±side
    side = 4
    for i in range(t.n):
        for k in range(t.degree[i]):
            d = abs(int(t.neighbors[i, k]) - i)
            assert d in (1, side)


def test_ref2d_is_a_line_q6():
    # Q6: the reference "2D" rounds up to a square then wires {i-1, i+1} only
    # (program.fs:242-248) — identical to the line builder over the rounded
    # population.
    t = T.build_ref2d(10, reference=True)
    assert t.n == 17 and t.target_count == 16  # 4² + the Q1 extra actor
    line = T.build_line(16, reference=True)
    assert (t.degree == line.degree).all()
    assert (t.neighbors == line.neighbors).all()


def test_imp2d_extra_edge():
    t = T.build_imp2d(16, seed=3)
    assert t.n == 16
    grid = T.build_grid2d(16)
    assert (t.degree == grid.degree + 1).all()
    for i in range(t.n):
        extra = int(t.neighbors[i, t.degree[i] - 1])
        assert extra != i and 0 <= extra < t.n


def test_grid3d_degrees():
    t = T.build_grid3d(27)
    assert t.n == 27
    deg = np.asarray(t.degree)
    assert (deg == 3).sum() == 8  # corners
    assert deg.max() == 6 and (deg == 6).sum() == 1  # single interior node
    a = dense_adj(t)
    assert (a == a.T).all()


def test_torus3d_regular():
    t = T.build_torus3d(27)
    assert t.n == 27 and (t.degree == 6).all()
    a = dense_adj(t)
    assert (a == a.T).all()
    # wraparound: node 0 adjacent to node g-1 along x
    assert a[0, 2]


def test_torus3d_rounds_down_to_cube():
    t = T.build_torus3d(1000000)
    assert t.n == 100**3


def test_imp3d_reference_rounding_c3_and_orphans_q8():
    # C3: n rounds down via floor(n**0.33334)**3 (program.fs:27-31).
    n = 100
    t = T.build_imp3d(n, seed=0, reference=True)
    rounded = int(math.floor(n**0.33334)) ** 3  # 4³ = 64
    assert rounded == 64
    assert t.n == rounded + 1  # Q1 extra actor
    assert t.target_count == rounded
    # Lattice side uses the *different* exponent floor(n**0.34)
    # (program.fs:268): g = 4 here, so all 64 lattice indices are wired and
    # only the Q1 extra is an orphan.
    assert t.degree[rounded] == 0
    wired = np.asarray(t.degree[:rounded])
    assert (wired >= 1).all() and (wired <= 7).all()


def test_imp3d_reference_orphans_from_exponent_mismatch():
    # Pick n where floor(n**0.33334)**3 > floor(n**0.34)**3 is impossible
    # (0.34 > 0.33334 ⇒ g >= cube side), so orphans beyond the lattice occur
    # only when rounded > g³ — verify the general invariant instead: every
    # index >= min(g³, rounded) has degree 0.
    for n in (50, 100, 333, 1000):
        t = T.build_imp3d(n, seed=1, reference=True)
        rounded = t.target_count
        g = int(math.floor(n**0.34))
        wired_limit = min(g**3, rounded)
        assert (np.asarray(t.degree[wired_limit:]) == 0).all()


def test_imp3d_reference_extra_edge_q9():
    # Q9: extra neighbor drawn from [0, rounded-1) — never the last lattice
    # index; self-edges and duplicates allowed.
    t = T.build_imp3d(1000, seed=0, reference=True)
    rounded = t.target_count
    extras = [
        int(t.neighbors[i, t.degree[i] - 1]) for i in range(rounded) if t.degree[i] > 0
    ]
    assert all(0 <= e < rounded - 1 for e in extras)


def test_imp3d_honest():
    t = T.build_imp3d(1000, seed=0)
    assert t.n == 1000  # exact cube kept
    deg = np.asarray(t.degree)
    assert (deg >= 4).all() and (deg <= 7).all()  # 3..6 grid + 1 extra
    for i in range(t.n):
        assert int(t.neighbors[i, t.degree[i] - 1]) != i  # extra edge j ≠ i


def test_build_topology_dispatch_and_validation():
    t = T.build_topology("line", 5, semantics="reference")
    assert t.n == 6
    with pytest.raises(ValueError):
        T.build_topology("hypercube", 5)
    for kind in ("line", "ring", "grid2d", "ref2d", "imp2d", "grid3d", "torus3d", "imp3d"):
        T.build_topology(kind, 64, seed=2).validate()
