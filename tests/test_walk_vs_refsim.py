"""Statistical cross-validation: JAX single-walk vs native DES (VERDICT r3 #6).

models/reference.py (JAX lax.while_loop walk) and native/refsim.cpp (C++
discrete-event queue) each replicate program.fs:110-143 independently, with
different RNGs — exact trajectory equality is impossible, so a semantic
drift in either replica is only catchable DISTRIBUTIONALLY. These tests
compare hops-to-convergence over many seeds: for push-sum both simulators
count exactly one processed message per hop (refsim's queue holds only
protocol messages; the walk's `steps` advances once per receipt), so the
distributions must agree up to sampling noise.

The oracle: |mean_a - mean_b| <= 4 * sqrt(var_a/n_a + var_b/n_b) + 2 — a
~4-sigma two-sample bound (false-alarm odds < 1e-4) with a +-2 slack for
kickoff-accounting offsets. Sensitivity, measured by perturbing one replica
(full n=16, 12-seed means): a delta-scale drift (1e-10 -> 1e-8) shifts the
mean -17% (~180 hops vs a ~58-hop bound at 50 seeds) — caught; a +-1
term_rounds tweak shifts it only 1-3% — below this test's resolution (the
last node's convergence is ratio-stability-dominated), so the termination
COUNTER is pinned by the unit oracles in test_reference_semantics.py, not
here.

Also pinned: the reference push-sum is a SINGLE walk — refsim proves it
dynamically (max_queue == 1); the JAX walk holds it by construction (the
carry has exactly one scalar in-flight (msg_s, msg_w) pair).
"""

import numpy as np
import pytest

import jax

from cop5615_gossip_protocol_tpu import SimConfig, build_topology, run
from cop5615_gossip_protocol_tpu.models.reference import WalkCarry
from cop5615_gossip_protocol_tpu.native import refsim_run


def _means_compatible(a, b, slack=2.0):
    a, b = np.asarray(a, float), np.asarray(b, float)
    gap = abs(a.mean() - b.mean())
    bound = 4.0 * np.sqrt(a.var(ddof=1) / len(a) + b.var(ddof=1) / len(b)) + slack
    return gap, bound


def _jax_hops(kind, n, seeds):
    hops = []
    for seed in seeds:
        cfg = SimConfig(n=n, topology=kind, algorithm="push-sum",
                        semantics="reference", dtype="float64", seed=seed,
                        max_rounds=10**6)
        r = run(build_topology(kind, n, semantics="reference"), cfg)
        assert r.converged, (kind, n, seed)
        hops.append(r.rounds)
    return hops


def _refsim_hops(kind, n, seeds):
    hops = []
    for seed in seeds:
        r = refsim_run(n, kind, "push-sum", seed=seed)
        assert r.ok and r.converged >= r.target, (kind, n, seed)
        assert r.max_queue == 1  # push-sum is a single walk, dynamically
        hops.append(r.events)
    return hops


@pytest.mark.skipif(not jax.config.jax_enable_x64,
                    reason="reference walk fidelity needs float64 (delta=1e-10)")
def test_pushsum_walk_hops_match_des_on_full():
    seeds = range(50)
    hops_j = _jax_hops("full", 16, seeds)
    hops_n = _refsim_hops("full", 16, seeds)
    gap, bound = _means_compatible(hops_j, hops_n)
    assert gap <= bound, (
        f"walk/DES hop means drifted: jax {np.mean(hops_j):.1f} vs "
        f"des {np.mean(hops_n):.1f} (gap {gap:.1f} > bound {bound:.1f})"
    )


@pytest.mark.skipif(not jax.config.jax_enable_x64,
                    reason="reference walk fidelity needs float64 (delta=1e-10)")
def test_pushsum_walk_hops_match_des_on_line():
    seeds = range(30)
    hops_j = _jax_hops("line", 10, seeds)
    hops_n = _refsim_hops("line", 10, seeds)
    gap, bound = _means_compatible(hops_j, hops_n)
    assert gap <= bound, (
        f"walk/DES hop means drifted: jax {np.mean(hops_j):.1f} vs "
        f"des {np.mean(hops_n):.1f} (gap {gap:.1f} > bound {bound:.1f})"
    )


def test_walk_single_message_by_construction():
    # The WalkCarry holds exactly one scalar in-flight mass pair — the
    # structural form of refsim's dynamic max_queue == 1 invariant.
    fields = WalkCarry._fields
    assert "msg_s" in fields and "msg_w" in fields
    # No sequence/queue-shaped in-flight storage exists in the carry.
    assert not any(f.startswith("queue") or f.startswith("inbox") for f in fields)
