"""Host-sharded construction pins (ISSUE 15 tentpole b).

The multi-host wall was never just device memory: a 2^30 run used to
materialize GLOBAL topology/plane arrays on one driver host before
sharding (to_planes' np.full(n_pad), init_state's arange, the adjacency
tensors). These tests pin the host-sharded build path — ops/topology's
``rows=(lo, hi)`` slice builds and the run functions' mesh.put_rows
fresh-plane builders — with an ALLOCATION TRACKER: every numpy array
creation on the build path is recorded, and the pin asserts no
intermediate of global-N elements is ever materialized for a sharded
run. A positive control proves the tracker sees what it claims (the
legacy full build DOES allocate N-element arrays).

The probe hook makes this cheap: the run functions build their planes,
then the probe short-circuits before any execution — so the pins run in
tier-1.
"""

import contextlib

import numpy as np
import pytest

import jax

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh

# 262144 = 64^3 torus and a 2048-row pool layout: big enough that a
# global-N allocation is unmistakable against the per-shard bound, small
# enough for tier-1.
N = 262_144
LANES = 128

_CREATORS = ("zeros", "ones", "full", "empty", "arange")


@contextlib.contextmanager
def track_numpy_allocs():
    """Record the largest array (in elements) any numpy creation function
    returns while active. Build-path code derives every large array from
    these creators (where/astype/reshape preserve size), so a bounded
    creator record bounds the build path's intermediates."""
    rec = {"max": 0}
    originals = {name: getattr(np, name) for name in _CREATORS}

    def wrap(fn):
        def inner(*args, **kw):
            out = fn(*args, **kw)
            if isinstance(out, np.ndarray):
                rec["max"] = max(rec["max"], out.size)
            return out

        return inner

    for name, fn in originals.items():
        setattr(np, name, wrap(fn))
    try:
        yield rec
    finally:
        for name, fn in originals.items():
            setattr(np, name, fn)


def _drop_probe(fn, args, **info):
    return None


def test_tracker_sees_the_legacy_global_build():
    # Positive control: the full (rows=None) torus build materializes the
    # [N, 6] adjacency — the tracker must see >= N elements, or the pins
    # below would pass vacuously.
    with track_numpy_allocs() as rec:
        build_topology("torus3d", N)
    assert rec["max"] >= N


def test_pool2_sharded_build_path_allocates_no_global_plane():
    # ISSUE 15 acceptance: the replicated-pool2 fresh build path (full
    # topology is implicit — no adjacency; state planes via
    # mesh.put_rows) materializes nothing bigger than one device's shard
    # rows. 4 devices -> shard = N/4 elements.
    from cop5615_gossip_protocol_tpu.parallel.pool2_sharded import (
        run_pool2_sharded,
    )

    n_dev = 4
    shard_elems = (N // LANES // n_dev) * LANES
    for algo in ("gossip", "push-sum"):
        cfg = SimConfig(n=N, topology="full", algorithm=algo,
                        delivery="pool", engine="fused", n_devices=n_dev,
                        chunk_rounds=1)
        with track_numpy_allocs() as rec:
            topo = build_topology("full", N)
            run_pool2_sharded(topo, cfg, mesh=make_mesh(n_dev),
                              probe=_drop_probe)
        assert rec["max"] <= shard_elems, (algo, rec["max"], shard_elems)


def test_hbm_sharded_build_path_allocates_no_global_plane():
    # The lattice composition: a SPEC-ONLY topology (rows=(0, 0) — kind/
    # population/offset structure, zero adjacency rows) plus per-shard
    # plane builders. Nothing on the build path may reach N elements.
    from cop5615_gossip_protocol_tpu.parallel.fused_hbm_sharded import (
        run_stencil_hbm_sharded,
    )

    n_dev = 2
    shard_elems = (N // LANES // n_dev) * LANES
    for algo in ("gossip", "push-sum"):
        cfg = SimConfig(n=N, topology="torus3d", algorithm=algo,
                        engine="fused", n_devices=n_dev, chunk_rounds=2)
        with track_numpy_allocs() as rec:
            topo = build_topology("torus3d", N, rows=(0, 0))
            run_stencil_hbm_sharded(topo, cfg, mesh=make_mesh(n_dev),
                                    probe=_drop_probe)
        assert rec["max"] < N, (algo, rec["max"])
        # The fresh planes are built shard-by-shard; allow small slack
        # for halo-extended geometry but nothing near global size.
        assert rec["max"] <= 2 * shard_elems, (algo, rec["max"])


def test_partial_topology_serves_only_fused_sharded_compositions():
    # The runner refuses a row-sliced topology everywhere a full
    # adjacency is gathered (chunked/single-device paths) — loudly, and
    # naming where it IS served.
    from cop5615_gossip_protocol_tpu.models.runner import run

    spec = build_topology("torus3d", N, rows=(0, 0))
    with pytest.raises(ValueError, match="host-sharded topology"):
        run(spec, SimConfig(n=N, topology="torus3d", engine="chunked",
                            strict_engine=True))
    with pytest.raises(ValueError, match="host-sharded topology"):
        run(spec, SimConfig(n=N, topology="torus3d", n_devices=2,
                            strict_engine=True))


def test_build_rows_contracts():
    # Reference semantics and imp kinds refuse the rows= path loudly
    # (sequential rng / small-N validation path); out-of-range slices
    # refuse; full is implicit (O(1) host) either way.
    with pytest.raises(ValueError, match="batched semantics"):
        build_topology("ring", 100, semantics="reference", rows=(0, 10))
    with pytest.raises(ValueError, match="sequential host rng"):
        build_topology("imp3d", 27_000, rows=(0, 10))
    with pytest.raises(ValueError, match="out of range"):
        build_topology("ring", 100, rows=(0, 101))
    full = build_topology("full", N, rows=(0, 0))
    assert full.implicit and not full.partial


def test_ranged_rows_match_full_build_both_sides_of_fallback():
    # The ranged builders (pop above the small-geometry fallback) and the
    # slice-of-full fallback produce byte-identical rows and the same
    # analytic stencil offsets as the full build.
    from cop5615_gossip_protocol_tpu.ops.topology import stencil_offsets

    for kind, n in (("torus3d", 4096), ("torus3d", N), ("ring", 1001),
                    ("line", 65536), ("grid2d", 20000),
                    ("grid3d", 20000), ("ref2d", 20000)):
        fullt = build_topology(kind, n)
        pop = fullt.n
        cuts = [0, pop // 3, pop // 2 + 1, pop]
        for lo, hi in zip(cuts, cuts[1:]):
            part = build_topology(kind, n, rows=(lo, hi))
            assert part.n == pop and part.max_deg == fullt.max_deg
            assert (part.neighbors == fullt.neighbors[lo:hi]).all()
            assert (part.degree == fullt.degree[lo:hi]).all()
        spec = build_topology(kind, n, rows=(0, 0))
        assert spec.partial
        assert (stencil_offsets(spec) == stencil_offsets(fullt)).all()


def test_kind_offsets_match_adjacency_scan():
    # The analytic displacement classes — what spec-only topologies serve
    # the sharded plans with — equal the O(N*deg) adjacency scan across
    # every arithmetic kind and a size sweep (degenerate tiny geometries
    # included).
    from cop5615_gossip_protocol_tpu.ops.topology import (
        kind_offsets,
        stencil_offsets,
    )

    sweep = {
        "line": (2, 3, 17, 1001, 20000),
        "ring": (2, 3, 17, 1001, 20000),
        "ref2d": (4, 10, 1001, 20000),
        "grid2d": (4, 10, 95, 1001, 20000),
        "grid3d": (8, 27, 1000, 20000),
        "torus3d": (8, 27, 4096, 125000),
    }
    for kind, sizes in sweep.items():
        for n in sizes:
            scan = stencil_offsets(build_topology(kind, n))
            ana = kind_offsets(kind, n)
            assert scan is not None and ana is not None, (kind, n)
            assert (scan == ana).all(), (kind, n, scan, ana)
    assert kind_offsets("full", 100) is None
    assert kind_offsets("imp3d", 27_000) is None


def test_finalize_result_process_spanning_fallback():
    # The multi-process finalize path (ISSUE 15 tentpole c): when the
    # state arrays report themselves non-host-addressable, the reductions
    # run as global jnp programs instead of np.asarray fetches — same
    # numbers. Simulated here by wrapping addressable arrays in a proxy
    # that denies addressability (this runtime has no gloo multiprocess
    # backend to do it for real — tests/_mp.py gates on that).
    import jax.numpy as jnp

    from cop5615_gossip_protocol_tpu.models.pushsum import PushSumState
    from cop5615_gossip_protocol_tpu.models.runner import _finalize_result

    n = 512

    class Remote:
        """jnp-compatible array proxy that claims to span processes."""

        is_fully_addressable = False

        def __init__(self, x):
            self._x = x

        def __jax_array__(self):
            return self._x

    s = jnp.arange(n, dtype=jnp.float32) * 2.0
    w = jnp.full((n,), 2.0, jnp.float32)
    conv = jnp.ones((n,), bool)
    topo = build_topology("full", n)
    cfg = SimConfig(n=n, topology="full", algorithm="push-sum")
    ref = _finalize_result(
        topo, cfg, PushSumState(s=s, w=w, term=None, conv=conv),
        rounds=7, target=n, compile_s=0.0, run_s=0.0, done=True,
    )
    got = _finalize_result(
        topo, cfg,
        PushSumState(s=Remote(s), w=Remote(w), term=None, conv=Remote(conv)),
        rounds=7, target=n, compile_s=0.0, run_s=0.0, done=True,
    )
    assert got.converged_count == ref.converged_count == n
    assert got.estimate_mae == pytest.approx(ref.estimate_mae, abs=1e-12)
