"""imp x HBM x sharded composition (parallel/fused_imp_hbm_sharded.py).

The marquee kind across chips (ISSUE 10): lattice classes delivered from
the halo-extended buffer (the one-sweep stencil machinery keyed by class
id), the pooled long-range classes from ONE all_gather of the windowed
send summaries per round. The design claim is BITWISE equality with the
single-device fused_imp_hbm engine at every device count — and
transitively with the chunked paths (the single-device engine is pinned
against them in tests/test_fused_imp_hbm.py); the chunked SHARDED engine
is pinned directly here too (the dual-oracle pattern of ISSUE 9).

Fast plan/gating/capability pins run in tier-1; interpret-mode kernel
oracles carry the slow mark (the ROADMAP tier-1 wall budget).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models.runner import run
from cop5615_gossip_protocol_tpu.ops import fused_imp, fused_imp_hbm
from cop5615_gossip_protocol_tpu.parallel.fused_imp_hbm_sharded import (
    plan_imp_hbm_sharded,
    plan_imp_hbm_sharded_shape,
    run_imp_hbm_sharded,
)

# 30^3 — the interpret-suite imp3d cube (padded layout 512 rows -> two
# 256-row shards; Z > 0 so the mod-n blend pair is live on the pool
# windows).
N3 = 27_000
# 256^2 — Z = 0, the single-window pool path.
N2 = 65_536


def _cfg(n, kind="imp3d", algorithm="gossip", **kw):
    kw.setdefault("delivery", "pool")
    kw.setdefault("engine", "fused")
    kw.setdefault("max_rounds", 300)
    if kw.get("n_devices"):
        kw.setdefault("chunk_rounds", 1)
    else:
        kw.setdefault("chunk_rounds", 16)
    return SimConfig(n=n, topology=kind, algorithm=algorithm, **kw)


@pytest.fixture
def force_hbm(monkeypatch):
    # Collapse the VMEM imp engine's budget so the single-device oracle
    # is the HBM-streaming tier this composition shards.
    monkeypatch.setattr(fused_imp, "_VMEM_BUDGET", 1000)


def _grab(final, tag):
    def f(rounds, state):
        final[tag] = state
    return f


# --- fast plan / gating / capability pins (tier-1) -------------------------


def test_plan_accepts_and_geometry_fits():
    for kind, n, nd in [("imp3d", N3, 2), ("imp3d", N3, 4),
                        ("imp2d", N2, 2), ("imp2d", N2, 4)]:
        plan = plan_imp_hbm_sharded(build_topology(kind, n),
                                    _cfg(n, kind, n_devices=nd), nd)
        assert not isinstance(plan, str), (kind, n, nd, plan)
        H, rows_loc, PT, layout = plan
        rows_ext = rows_loc + 2 * H
        assert rows_loc * nd == layout.rows
        assert rows_ext % PT == 0
        # Mirror margins must fit one ring revolution (the round-3
        # boundary-corruption regression: a clipped margin clamps the
        # window DMAs silently).
        from cop5615_gossip_protocol_tpu.parallel.fused_imp_hbm_sharded \
            import _imp_lat_plan
        _cls, _grp, m_lat = _imp_lat_plan(kind, layout, rows_ext, PT)
        assert m_lat <= rows_ext
        assert PT + 16 <= layout.rows


def test_plan_level_ceiling_past_2_28():
    # The BENCH_TABLES "topology ceilings" imp row, hardware-free: the
    # plan (a pure function of shape) admits an imp3d population past
    # 2^28 aggregate on an 8-device mesh — vs the reference's 2,000-actor
    # cap and the single-device engine's 2^27 HBM budget.
    n = 648 ** 3  # 272,097,792 > 2^28
    assert n >= 1 << 28
    for algorithm in ("push-sum", "gossip"):
        plan = plan_imp_hbm_sharded_shape(
            "imp3d", n, _cfg(n, algorithm=algorithm, n_devices=8), 8
        )
        assert not isinstance(plan, str), plan
    # and refuses honestly when one device's gathered copy cannot fit
    big = 4096 ** 3
    reason = plan_imp_hbm_sharded_shape(
        "imp3d", big, _cfg(big, n_devices=8), 8
    )
    assert isinstance(reason, str)


def test_plan_gating_reasons():
    cfg = _cfg(N3, n_devices=2)
    topo = build_topology("imp3d", N3)
    assert "not an imp" in plan_imp_hbm_sharded(
        build_topology("torus3d", 4096), cfg, 2
    )
    assert "delivery='pool'" in plan_imp_hbm_sharded(
        topo, _cfg(N3, delivery="auto", n_devices=2), 2
    )
    assert "perfect cube" in plan_imp_hbm_sharded_shape(
        "imp3d", 27_001, cfg, 2
    )
    assert "perfect square" in plan_imp_hbm_sharded_shape(
        "imp2d", 27_001, cfg, 2
    )
    assert "failure models" in plan_imp_hbm_sharded(
        topo, _cfg(N3, n_devices=2, fault_rate=0.1), 2
    )
    assert "telemetry" in plan_imp_hbm_sharded(
        topo, _cfg(N3, n_devices=2, telemetry=True), 2
    )
    assert "float32" in plan_imp_hbm_sharded(
        topo, _cfg(N3, n_devices=2, dtype="bfloat16"), 2
    )
    assert "static extra edge" in plan_imp_hbm_sharded(
        build_topology("imp3d", N3, semantics="reference"),
        _cfg(N3, n_devices=2, semantics="reference"), 2
    )


def test_capability_messages_name_the_sharded_composition():
    # Capability-matrix honesty (ISSUE 10): the single-device support
    # messages must tell the caller the sharded composition exists
    # instead of a dead-end "single-device" shrug.
    topo = build_topology("imp3d", N3)
    msg = fused_imp_hbm.imp_hbm_support(topo, _cfg(N3, n_devices=2))
    assert "single-device" in msg and "fused_imp_hbm_sharded" in msg
    # the stencil sharded plan routes imp kinds to this composition
    from cop5615_gossip_protocol_tpu.parallel.fused_hbm_sharded import (
        plan_stencil_hbm_sharded,
    )
    reason = plan_stencil_hbm_sharded(topo, _cfg(N3, n_devices=2,
                                                 delivery="auto"), 2)
    assert "imp x HBM x sharded" in reason


def test_halo_dma_on_is_trace_only_off_tpu():
    # halo_dma='on' builds the in-kernel async-remote-copy program, which
    # EXECUTES only on TPU; a CPU execution attempt must refuse with the
    # knob guidance (the comm-audit probe traces it hardware-free —
    # tests/test_comm_audit.py pins those counts).
    topo = build_topology("imp3d", N3)
    with pytest.raises(ValueError, match="halo_dma"):
        run_imp_hbm_sharded(topo, _cfg(N3, n_devices=2, halo_dma="on"))


def test_loud_refusal_and_auto_demotion():
    # engine='fused' with an unserveable config refuses loudly with the
    # plan reason...
    topo = build_topology("imp3d", N3)
    with pytest.raises(ValueError, match="engine='fused'"):
        run_imp_hbm_sharded(topo, _cfg(N3, n_devices=2, telemetry=False,
                                       fault_rate=0.1))
    # ...while engine='auto' (the default) never reaches the fused
    # compositions under sharding: the run demotes to the sharded XLA
    # engine without any ValueError escaping to the user.
    n = 1024  # 32^2 — small enough for a real XLA run in tier-1
    r = run(build_topology("imp2d", n),
            SimConfig(n=n, topology="imp2d", algorithm="gossip",
                      delivery="pool", n_devices=2, max_rounds=200))
    assert r.rounds > 0


# --- interpret-mode kernel oracles (slow suite) ----------------------------


@pytest.mark.slow
def test_gossip_bitwise_vs_single_device_and_chunked_sharded(force_hbm):
    # Dual oracle (the ISSUE 9 pattern): the composition must match the
    # single-device HBM engine it shards AND the chunked sharded engine.
    topo = build_topology("imp3d", N3)
    r_hbm = run(topo, _cfg(N3))
    r_chk = run(topo, _cfg(N3, engine="chunked", n_devices=2,
                           chunk_rounds=8))
    for ov in (True, False):
        r_sh = run(topo, _cfg(N3, n_devices=2, overlap_collectives=ov))
        assert r_sh.rounds == r_hbm.rounds == r_chk.rounds
        assert (r_sh.converged_count == r_hbm.converged_count
                == r_chk.converged_count)


@pytest.mark.slow
@pytest.mark.parametrize("kind,n", [("imp3d", N3), ("imp2d", N2)])
def test_pushsum_state_bitwise(kind, n, force_hbm):
    topo = build_topology(kind, n)
    final = {}
    r = run(topo, _cfg(n, kind, algorithm="push-sum", max_rounds=48,
                       chunk_rounds=48),
            on_chunk=_grab(final, "single"))
    assert r.rounds == 48
    for nd in (2, 4):
        r = run(topo, _cfg(n, kind, algorithm="push-sum", n_devices=nd,
                           max_rounds=48),
                on_chunk=_grab(final, "sh"))
        assert r.rounds == 48
        for f in ("s", "w", "term", "conv"):
            a = np.asarray(getattr(final["single"], f))[:n]
            b = np.asarray(getattr(final["sh"], f))[:n]
            assert (a != b).sum() == 0, (kind, nd, f)


@pytest.mark.slow
def test_pushsum_global_termination_exact(force_hbm):
    topo = build_topology("imp3d", N3)
    r1 = run(topo, _cfg(N3, algorithm="push-sum", termination="global",
                        delta=1e-1, max_rounds=500, chunk_rounds=16))
    r2 = run(topo, _cfg(N3, algorithm="push-sum", termination="global",
                        delta=1e-1, max_rounds=500, n_devices=2))
    assert r1.rounds == r2.rounds
    assert r1.converged_count == r2.converged_count


@pytest.mark.slow
def test_resume_midway(force_hbm):
    topo = build_topology("imp3d", N3)
    snap = {}

    def keep(rounds, state):
        snap.setdefault("s0", (rounds, state))

    full = run(topo, _cfg(N3, n_devices=2), on_chunk=keep)
    rounds0, s0 = snap["s0"]
    assert 0 < rounds0 < full.rounds
    resumed = run(topo, _cfg(N3, n_devices=2),
                  start_state=jax.tree.map(jnp.asarray, s0),
                  start_round=rounds0)
    assert resumed.rounds == full.rounds
    assert resumed.converged_count == full.converged_count
