#!/usr/bin/env bash
# Tier-1 verify — the ROADMAP.md command, verbatim. CI and local runs share
# this one definition so "tier-1 green" means the same thing everywhere.
# The DOTS_PASSED count prints from an EXIT trap so every exit path —
# pytest failures, the timeout kill, an unexpected bash error — still
# reports how many tests got through before the run ended.
set -o pipefail
rm -f /tmp/_t1.log

# CI must never silently degrade engines: a fused/sharded failure under
# tier-1 is a bug, not a condition to recover from (models/runner.py
# honors this env var over cfg.strict_engine). The degradation ladder
# itself is still exercised — by the explicit ladder tests (which locally
# override the var to 0) and by the chaos CI job.
export GOSSIP_TPU_STRICT_ENGINE=1

# Comm-volume pins ride inside the suite below (tests/test_comm_audit.py:
# collectives per round/super-step traced from the real jitted chunks —
# the batched-wire contract of ISSUE 5 fails here on CPU, no TPU needed);
# the human-readable table is the CI bench-smoke artifact
# (`python benchmarks/comm_audit.py`).

print_dots() {
  echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log 2>/dev/null | tr -cd . | wc -c)"
}
trap print_dots EXIT

# Budget 1200 s (was 870, set when the suite was ~450 tests): the suite
# has grown to ~580 tier-1 tests across twelve PRs and a quiet run on the
# 2-core CI-class box now takes ~740-880 s with ±15% host noise — the old
# budget was killing CLEAN runs at 99%. The timeout exists to catch hangs
# (the reference's line-topology freeze class), not to cap suite growth;
# 1200 still fails a wedged run well inside the CI job limit.
timeout -k 10 1200 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
exit ${PIPESTATUS[0]}
