"""multihost-smoke (ISSUE 15): the two-OS-process gloo bring-up on every
push — one composition driven bitwise against its single-process oracle,
plus the comm-audit table of the multi-process-serving compositions as a
CI artifact.

Flow per composition:
  1. run the single-process 8-virtual-device oracle in THIS process;
  2. spawn TWO coordinated OS processes of the public CLI over a gloo
     coordinator (tests/_mp.py — the same harness the slow pytest pins
     use), each hosting half the global mesh;
  3. assert the lead record's (rounds, converged_count) match exactly —
     gossip state is integer and the stream is process-count-invariant.

Compositions driven: the chunked sharded engine (torus3d halo wire) and
replicated-pool2 via delivery='matmul' (its banded reduce_scatter wire
crossing the process boundary; 8 capped rounds — interpret mode).

SKIP-GATED like the slow pytest suite: a jaxlib whose CPU client has no
cross-process collectives (no gloo) exits 0 with a loud SKIP line — any
OTHER child failure fails the job.

Usage: python scripts/multihost_smoke.py [--audit-json FILE --audit-md FILE]
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--audit-json", type=Path, default=None)
    ap.add_argument("--audit-md", type=Path, default=None)
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from cop5615_gossip_protocol_tpu.utils import compat

    jax.config.update("jax_threefry_partitionable", True)
    compat.set_host_device_count(8)

    from tests._mp import SkipUnsupported, spawn_procs

    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.models.runner import run

    # Comm-audit artifact first (works with or without gloo): the traced
    # wire tables of the compositions the multi-process tier serves —
    # chunked sharded, HBM-streaming sharded, and replicated-pool2 (both
    # wires; the banded reduce_scatter rows carry the ISSUE 15 recv-bytes
    # delta).
    from benchmarks.comm_audit import table as audit_table

    from cop5615_gossip_protocol_tpu.analysis.trace import audit_engine

    cells = (
        ("sharded", "torus3d", "gossip", 4096, 8, {}),
        ("hbm-sharded", "torus3d", "gossip", 125000, 2,
         {"engine": "fused", "chunk_rounds": 8}),
        ("pool2-sharded", "full", "gossip", 262144, 8,
         {"engine": "fused", "delivery": "pool"}),
        ("pool2-sharded", "full", "gossip", 262144, 8,
         {"engine": "fused", "delivery": "pool",
          "pool2_wire": "all_gather"}),
    )
    reports = [
        audit_engine(engine, topo, algo, n, n_dev, True, extra)
        for engine, topo, algo, n, n_dev, extra in cells
    ]
    md = "\n".join(
        ["# multihost-smoke comm audit (multi-process-serving "
         "compositions)", ""] + audit_table(reports)
    )
    print(md)
    if args.audit_md:
        args.audit_md.write_text(md + "\n")
    if args.audit_json:
        with open(args.audit_json, "w") as f:
            for r in reports:
                f.write(json.dumps(r.to_record()) + "\n")

    def drive(label, cli_args, oracle, expect_rc=(0,)):
        with tempfile.TemporaryDirectory() as td:
            rec, _logs = spawn_procs(
                Path(td), cli_args, n_procs=2, devices=8,
                expect_rc=expect_rc, timeout=600,
            )
        assert rec["rounds"] == oracle.rounds, (
            label, rec["rounds"], oracle.rounds
        )
        assert rec["converged_count"] == oracle.converged_count, label
        print(f"[multihost-smoke] {label} bitwise OK "
              f"({rec['rounds']} rounds, conv {rec['converged_count']})")

    try:
        n = 4096
        ref = run(
            build_topology("torus3d", n),
            SimConfig(n=n, topology="torus3d", algorithm="gossip",
                      n_devices=8),
        )
        drive("chunked sharded torus3d", [str(n), "torus3d", "gossip"], ref)

        n2 = 262_144
        from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh
        from cop5615_gossip_protocol_tpu.parallel.pool2_sharded import (
            run_pool2_sharded,
        )

        ref2 = run_pool2_sharded(
            build_topology("full", n2),
            SimConfig(n=n2, topology="full", algorithm="gossip",
                      delivery="matmul", engine="fused", chunk_rounds=1,
                      max_rounds=8, n_devices=8),
            mesh=make_mesh(8),
        )
        drive(
            "replicated-pool2 (reduce_scatter wire)",
            [str(n2), "full", "gossip", "--delivery", "matmul",
             "--engine", "fused", "--max-rounds", "8",
             "--chunk-rounds", "1"],
            ref2, expect_rc={0, 1},
        )
    except SkipUnsupported as e:
        print(f"[multihost-smoke] SKIP (gloo runs): {e}")
        return 0
    print("[multihost-smoke] all compositions bitwise across processes")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
