#!/usr/bin/env python
"""Chaos harness: SIGKILL a checkpointing CLI run mid-flight, resume it,
and assert the recovery contracts hold end to end.

What it proves (the crash-only-restarts story, CI-enforced):

1. **Kill-resume completes** — a run with ``--checkpoint-every``/``--events``
   killed with SIGKILL (no cleanup handlers, the honest preemption model)
   reruns with ``--resume auto`` and finishes with exit 0.
2. **Event-log consistency** — the shared events file reads back as
   run-start -> checkpoint-written... -> (second) run-start -> resume ->
   ... -> run-end, with the resume round equal to a previously written
   checkpoint round and exactly one run-end, outcome=converged.
3. **Bitwise-resume invariant** — the killed+resumed run's final record
   (rounds, converged_count, estimate) equals an uninterrupted control run
   of the identical config, byte for byte on those fields.
4. **Degradation ladder liveness** (``--ladder``) — with strict mode off, a
   run whose first-choice engine dies environmentally walks
   fused/sharded -> chunked/single-device (models/runner.run), emits a
   structured engine-degraded event, and still returns the right answer.
5. **Byzantine kill-resume** (ISSUE 16) — the same kill-resume contract
   with 16 mass_inflate adversaries turning at round 500 under the clip
   countermeasure: the adversary plane is never checkpointed, so bitwise
   equality with the control proves the resumed process rebuilt the
   identical plane (same nodes, same onset) from the config alone.
6. **I/O-fault corruption legs** (ISSUE 19, ``--corrupt``) — the
   GOSSIP_TPU_CKPT_FAULT injector corrupts a checkpoint write in a real
   CLI subprocess:
   ``torn``/``flip`` truncate / bit-flip the just-renamed archive and kill
   the process (exit 17/19); the resume must QUARANTINE the corrupt
   generation (one checkpoint-corrupt-quarantined event, ``*.corrupt``
   files on disk), fall back to the newest intact generation, and finish
   bitwise-equal to the control. ``enospc`` makes every save from the
   third on fail with injected ENOSPC; the run must CONTINUE under the
   default lose-one-interval policy (checkpoint-failed events post-run)
   and still match the control bitwise.

Usage: python scripts/chaos_kill_resume.py [--ladder-only] [--kill-after S]
       python scripts/chaos_kill_resume.py --corrupt torn [--out-dir D]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

# A run long enough on CI CPUs that a kill lands mid-flight: push-sum on a
# line mixes in O(n^2) rounds (~16.5k rounds / ~8 s at n=1600 on a 2-core
# dev box). chunk_rounds keeps checkpoints frequent (one per ~256 rounds).
CONFIG = ["1600", "line", "push-sum", "--seed", "3", "--platform", "cpu",
          "--chunk-rounds", "256", "--max-rounds", "400000",
          "--delivery", "scatter"]

# The Byzantine variant of the same run (ISSUE 16): 16 adversaries turn at
# round 500 in mass_inflate mode, bounded by the clip countermeasure (the
# sentinel is config-excluded under robust_agg). mass_inflate preserves the
# sender's s/w RATIO, so the line still converges — what the kill tests is
# that the adversary plane is NEVER checkpointed: the resumed process must
# rebuild the identical 16 adversaries (and their onset round) from the
# config alone, or the bitwise-resume invariant breaks.
BYZ_EXTRA = ["--byzantine-schedule", "500:16",
             "--byzantine-mode", "mass_inflate", "--robust-agg", "clip"]


def _cli(extra, env=None, config=CONFIG):
    e = dict(os.environ, JAX_PLATFORMS="cpu")
    if env:
        e.update(env)
    return subprocess.Popen(
        [sys.executable, "-m", "cop5615_gossip_protocol_tpu", *config,
         *extra],
        cwd=REPO, env=e, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )


def _read_jsonl(path):
    out = []
    for line in Path(path).read_text().splitlines():
        if line.strip():
            out.append(json.loads(line))
    return out


def fail(msg):
    print(f"CHAOS FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def kill_resume(kill_after: float, config=CONFIG,
                label: str = "kill-resume") -> None:
    tmp = Path(tempfile.mkdtemp(prefix="gossip_chaos_"))
    ck = tmp / "ck.npz"
    ev = tmp / "events.jsonl"
    rec_victim = tmp / "victim.jsonl"
    rec_control = tmp / "control.jsonl"

    print(f"[chaos] {label}: control run (uninterrupted)...")
    p = _cli(["--quiet", "--jsonl", str(rec_control)], config=config)
    out, err = p.communicate(timeout=1800)
    if p.returncode != 0:
        fail(f"control run failed rc={p.returncode}: {err.decode()[-800:]}")
    control = _read_jsonl(rec_control)[-1]
    print(f"[chaos] control: rounds={control['rounds']} "
          f"outcome={control['outcome']}")

    common = ["--quiet", "--checkpoint", str(ck), "--checkpoint-every", "1",
              "--events", str(ev), "--resume", "auto",
              "--jsonl", str(rec_victim)]
    print("[chaos] victim run, waiting for first checkpoint then SIGKILL...")
    p = _cli(common, config=config)
    deadline = time.time() + 600
    while not ck.exists() and time.time() < deadline:
        if p.poll() is not None:
            fail("victim finished before a checkpoint was written — "
                 "config too fast for this machine; raise n/max_rounds")
        time.sleep(0.05)
    if not ck.exists():
        fail("no checkpoint appeared within 600s")
    time.sleep(kill_after)  # let a few more chunks retire
    if p.poll() is not None:
        fail("victim finished before the kill landed — config too fast")
    p.send_signal(signal.SIGKILL)
    p.wait()
    print(f"[chaos] killed victim (rc={p.returncode})")
    if any(e["event"] == "run-end" for e in _read_jsonl(ev)):
        fail("victim's event log already has run-end — the kill landed "
             "after completion, nothing was tested")

    print("[chaos] resuming with --resume auto...")
    p = _cli(common, config=config)
    out, err = p.communicate(timeout=1800)
    if p.returncode != 0:
        fail(f"resume run failed rc={p.returncode}: {err.decode()[-800:]}")

    events = _read_jsonl(ev)
    kinds = [e["event"] for e in events]
    if kinds[0] != "run-start":
        fail(f"first event is {kinds[0]!r}, want run-start")
    if kinds.count("run-start") != 2:
        fail(f"want exactly 2 run-start events (victim + resume), "
             f"got {kinds.count('run-start')}")
    if kinds.count("run-end") != 1:
        fail(f"want exactly 1 run-end (the resumed run's), got "
             f"{kinds.count('run-end')}")
    if kinds[-1] != "run-end":
        fail(f"last event is {kinds[-1]!r}, want run-end")
    resumes = [e for e in events if e["event"] == "resume"]
    if len(resumes) != 1:
        fail(f"want exactly 1 resume event, got {len(resumes)}")
    ck_rounds = {e["rounds"] for e in events
                 if e["event"] == "checkpoint-written"}
    if resumes[0]["rounds"] not in ck_rounds:
        fail(f"resume round {resumes[0]['rounds']} matches no "
             f"checkpoint-written round {sorted(ck_rounds)}")
    second_start = kinds.index("run-start", 1)
    if "resume" not in kinds[second_start:]:
        fail("resume event does not follow the second run-start")
    run_end = [e for e in events if e["event"] == "run-end"][0]
    if run_end["outcome"] != "converged":
        fail(f"resumed run outcome={run_end['outcome']}, want converged")

    victim = _read_jsonl(rec_victim)[-1]
    for field in ("rounds", "converged_count", "outcome", "estimate_mae",
                  "converged"):
        if victim[field] != control[field]:
            fail(f"bitwise-resume invariant broken: {field} "
                 f"{victim[field]!r} != control {control[field]!r}")
    print(f"[chaos] {label} OK: rounds={victim['rounds']} bitwise-equal "
          f"to control, event log consistent ({len(events)} events)")


# Exit codes the env-gated fault injector uses for its simulated
# post-write kills (utils/checkpoint._env_fault).
FAULT_RC = {"torn": 17, "flip": 19}


def corrupt_leg(mode: str, out_dir=None) -> None:
    """One --corrupt leg: inject a checkpoint I/O fault via
    GOSSIP_TPU_CKPT_FAULT in a real CLI subprocess and assert the
    recovery (torn/flip) or continue-under-failure (enospc) contract."""
    if out_dir is None:
        tmp = Path(tempfile.mkdtemp(prefix=f"gossip_chaos_{mode}_"))
    else:
        tmp = Path(out_dir)
        tmp.mkdir(parents=True, exist_ok=True)
        for stale in list(tmp.glob("ck*")) + list(tmp.glob("*.jsonl")):
            stale.unlink()
    ck = tmp / "ck.npz"
    ev = tmp / "events.jsonl"
    rec_victim = tmp / "victim.jsonl"
    rec_control = tmp / "control.jsonl"

    print(f"[chaos] corrupt-{mode}: control run (uninterrupted)...")
    p = _cli(["--quiet", "--jsonl", str(rec_control)])
    out, err = p.communicate(timeout=1800)
    if p.returncode != 0:
        fail(f"control run failed rc={p.returncode}: {err.decode()[-800:]}")
    control = _read_jsonl(rec_control)[-1]
    print(f"[chaos] control: rounds={control['rounds']} "
          f"outcome={control['outcome']}")

    common = ["--quiet", "--checkpoint", str(ck), "--checkpoint-every", "1",
              "--checkpoint-keep", "3", "--events", str(ev),
              "--resume", "auto", "--jsonl", str(rec_victim)]
    # Fault the third save (zero-indexed 2) so two intact generations
    # precede the corruption; enospc fails every save from there on.
    spec = {"torn": "torn:2", "flip": "flip:2",
            "enospc": "enospc:2:1000000"}[mode]
    print(f"[chaos] corrupt-{mode}: victim with "
          f"GOSSIP_TPU_CKPT_FAULT={spec}...")
    p = _cli(common, env={"GOSSIP_TPU_CKPT_FAULT": spec})
    out, err = p.communicate(timeout=1800)

    if mode == "enospc":
        # The lose-one-interval policy end to end: the run keeps going
        # past every failed save, converges with exit 0, and reports the
        # failures as post-run checkpoint-failed events.
        if p.returncode != 0:
            fail(f"enospc victim failed rc={p.returncode} — the default "
                 f"hook_error=continue policy should have absorbed the "
                 f"injected ENOSPC: {err.decode()[-800:]}")
        events = _read_jsonl(ev)
        fails = [e for e in events if e["event"] == "checkpoint-failed"]
        if not fails:
            fail("no checkpoint-failed events despite injected ENOSPC")
        if any("ENOSPC" not in f["error"] and "No space" not in f["error"]
               for f in fails):
            fail(f"checkpoint-failed error text surprising: {fails[:2]}")
        ends = [e for e in events if e["event"] == "run-end"]
        if len(ends) != 1 or ends[0]["outcome"] != "converged":
            fail(f"want 1 converged run-end, got {ends}")
        victim = _read_jsonl(rec_victim)[-1]
        for field in ("rounds", "converged_count", "outcome",
                      "estimate_mae", "converged"):
            if victim[field] != control[field]:
                fail(f"enospc continue policy changed the run: {field} "
                     f"{victim[field]!r} != control {control[field]!r}")
        print(f"[chaos] corrupt-enospc OK: {len(fails)} failed saves "
              f"absorbed, run bitwise-equal to control")
        return

    want_rc = FAULT_RC[mode]
    if p.returncode != want_rc:
        fail(f"corrupt-{mode} victim exited rc={p.returncode}, want "
             f"{want_rc} (the injected post-write kill): "
             f"{err.decode()[-800:]}")
    if any(e["event"] == "run-end" for e in _read_jsonl(ev)):
        fail("victim's event log already has run-end — the fault landed "
             "after completion, nothing was tested")

    print(f"[chaos] corrupt-{mode}: resuming with --resume auto "
          f"(fault env cleared)...")
    p = _cli(common)
    out, err = p.communicate(timeout=1800)
    if p.returncode != 0:
        fail(f"resume run failed rc={p.returncode}: {err.decode()[-800:]}")

    events = _read_jsonl(ev)
    quar = [e for e in events
            if e["event"] == "checkpoint-corrupt-quarantined"]
    if len(quar) != 1:
        fail(f"want exactly 1 checkpoint-corrupt-quarantined event, "
             f"got {len(quar)}")
    for fld in ("path", "reason", "quarantined"):
        if fld not in quar[0]:
            fail(f"quarantine event missing {fld!r}: {quar[0]}")
    if not list(tmp.glob("*.corrupt")):
        fail("no *.corrupt quarantine artifacts on disk")
    resumes = [e for e in events if e["event"] == "resume"]
    if len(resumes) != 1:
        fail(f"want exactly 1 resume event, got {len(resumes)}")
    ck_rounds = {e["rounds"] for e in events
                 if e["event"] == "checkpoint-written"}
    if resumes[0]["rounds"] not in ck_rounds:
        fail(f"resume round {resumes[0]['rounds']} matches no "
             f"checkpoint-written round {sorted(ck_rounds)}")
    ends = [e for e in events if e["event"] == "run-end"]
    if len(ends) != 1 or ends[0]["outcome"] != "converged":
        fail(f"want 1 converged run-end, got {ends}")
    victim = _read_jsonl(rec_victim)[-1]
    for field in ("rounds", "converged_count", "outcome", "estimate_mae",
                  "converged"):
        if victim[field] != control[field]:
            fail(f"bitwise-resume invariant broken after corrupt-{mode}: "
                 f"{field} {victim[field]!r} != control "
                 f"{control[field]!r}")
    print(f"[chaos] corrupt-{mode} OK: quarantined "
          f"({quar[0]['reason'][:60]}...), resumed from round "
          f"{resumes[0]['rounds']}, bitwise-equal to control "
          f"({len(events)} events)")


def ladder() -> None:
    """Exercise the degradation ladder with a real (injected) engine
    failure: sharded dispatch dies environmentally, the run must complete
    single-device and log the rung walk."""
    code = r"""
import os
os.environ["GOSSIP_TPU_STRICT_ENGINE"] = "0"
os.environ["GOSSIP_TPU_RETRY_BASE_S"] = "0"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
).strip()
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)
from cop5615_gossip_protocol_tpu import SimConfig, build_topology
from cop5615_gossip_protocol_tpu.models import runner
from cop5615_gossip_protocol_tpu.parallel import sharded

calls = {"n": 0}
def boom(*a, **k):
    calls["n"] += 1
    raise RuntimeError("chaos-injected: device UNAVAILABLE" if calls["n"] <= 1
                       else "chaos-injected: hard engine failure")
sharded.run_sharded = boom

events = []
cfg = SimConfig(n=128, topology="full", algorithm="gossip", n_devices=2,
                chunk_rounds=32)
r = runner.run(build_topology("full", 128), cfg,
               on_event=lambda ev, **f: events.append((ev, f)))
assert r.converged, r.outcome
# Two rungs walked: auto/2dev -> chunked/2dev (still sharded, still dies)
# -> chunked/1dev (succeeds). The transient UNAVAILABLE error was retried
# with backoff before the first rung moved.
assert r.degradations and len(r.degradations) == 2, r.degradations
assert r.degradations[0]["transient_retries"] >= 1, r.degradations
assert "devices=1" in r.degradations[-1]["to"], r.degradations
assert len(events) == 2 and all(
    ev == "engine-degraded" for ev, _ in events
), events
print("[chaos] ladder OK:", " -> ".join(
    [r.degradations[0]["from"]] + [d["to"] for d in r.degradations]),
    f"({r.degradations[0]['transient_retries']} transient retries);",
    "rounds", r.rounds)
"""
    p = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=600)
    sys.stdout.write(p.stdout)
    if p.returncode != 0:
        fail(f"ladder scenario failed:\n{p.stderr[-2000:]}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ladder-only", action="store_true",
                    help="run only the degradation-ladder scenario")
    ap.add_argument("--kill-after", type=float, default=2.0,
                    help="extra seconds after the first checkpoint before "
                    "the SIGKILL lands")
    ap.add_argument("--corrupt", action="append",
                    choices=sorted({"torn", "flip", "enospc"}),
                    help="run only the named I/O-fault corruption leg(s) "
                    "(repeatable) instead of the kill-resume scenarios")
    ap.add_argument("--out-dir", default=None,
                    help="working directory for --corrupt legs (kept, so "
                    "CI can upload events.jsonl + *.corrupt artifacts); "
                    "default: fresh tempdir")
    args = ap.parse_args(argv)
    if args.corrupt:
        for mode in args.corrupt:
            corrupt_leg(mode, out_dir=args.out_dir)
        print("[chaos] all scenarios passed")
        return 0
    ladder()
    if not args.ladder_only:
        kill_resume(args.kill_after)
        kill_resume(args.kill_after, config=CONFIG + BYZ_EXTRA,
                    label="byzantine kill-resume")
    print("[chaos] all scenarios passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
