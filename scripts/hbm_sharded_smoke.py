"""CI smoke of the one-sweep HBM-streaming x sharded composition
(ISSUE 9): a short interpret-mode run on a 2-virtual-CPU-device mesh must
match the single-device chunked engine bitwise, and the in-kernel-DMA
transport must trace with zero XLA collectives on the halo path. Small on
purpose (ring at 2^16, a handful of rounds) — the exhaustive oracles are
the slow suite (tests/test_fused_hbm_sharded.py); this keeps the
composition path executing end-to-end on every push.

Usage: python scripts/hbm_sharded_smoke.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from cop5615_gossip_protocol_tpu.utils import compat

    jax.config.update("jax_threefry_partitionable", True)
    compat.set_host_device_count(2)

    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.models.runner import run
    from cop5615_gossip_protocol_tpu.parallel.fused_hbm_sharded import (
        run_stencil_hbm_sharded,
    )
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh

    n = 65536
    rounds = 24
    topo = build_topology("ring", n)
    grab = {}
    r1 = run(
        topo,
        SimConfig(n=n, topology="ring", algorithm="gossip",
                  engine="chunked", max_rounds=rounds, chunk_rounds=rounds),
        on_chunk=lambda r, s: grab.update(a=s),
    )
    cfg = SimConfig(n=n, topology="ring", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=2,
                    max_rounds=rounds)
    r2 = run_stencil_hbm_sharded(
        topo, cfg, mesh=make_mesh(2), on_chunk=lambda r, s: grab.update(b=s)
    )
    assert r1.rounds == r2.rounds == rounds, (r1.rounds, r2.rounds)
    assert r1.converged_count == r2.converged_count
    for f in ("count", "active", "conv"):
        a = np.asarray(getattr(grab["a"], f))
        b = np.asarray(getattr(grab["b"], f))[:n]
        assert (a == b).all(), f"{f} diverged"
    print(f"[hbm-sharded-smoke] one-sweep fallback bitwise OK "
          f"({rounds} rounds, conv {r2.converged_count})")

    # DMA-transport trace: zero XLA collectives on the halo path.
    cfg_dma = SimConfig(n=n, topology="ring", algorithm="gossip",
                        engine="fused", n_devices=2, chunk_rounds=2,
                        max_rounds=rounds, halo_dma="on")
    probed = {}

    def probe(fn, args):
        probed["txt"] = str(jax.make_jaxpr(fn)(*args))
        return None

    run_stencil_hbm_sharded(topo, cfg_dma, mesh=make_mesh(2), probe=probe)
    assert "ppermute" not in probed["txt"], "DMA path still carries ppermute"
    assert "dma_start" in probed["txt"]
    print("[hbm-sharded-smoke] in-kernel-dma trace OK (no ppermute)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
