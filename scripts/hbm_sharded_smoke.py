"""CI smoke of the HBM-streaming x sharded compositions: short
interpret-mode runs on a 2-virtual-CPU-device mesh must match the
single-device chunked engine bitwise, and the in-kernel-DMA transport
must trace with zero XLA collectives on the halo path. Small on purpose
(a handful of rounds each) — the exhaustive oracles are the slow suite;
this keeps the composition paths executing end-to-end on every push.

- one-sweep stencil composition (ISSUE 9): ring at 2^16, bitwise counts
  + the DMA-transport trace (tests/test_fused_hbm_sharded.py);
- imp x HBM x sharded (ISSUE 10): imp3d at 30^3 — lattice halo windows +
  the pooled long-range all_gather, bitwise counts vs the chunked
  engine + the DMA trace (tests/test_fused_imp_hbm_sharded.py);
- replicated-pool2 (ISSUE 10): the full topology at 2^18, ONE all_gather
  of the send summaries per round, bitwise counts vs the chunked pool
  path (tests/test_pool2_sharded.py);
- MXU matmul tier (ISSUE 12): the chunked one-hot dot_general round AND
  the replicated-pool2 composition with the per-shard one-hot MXU blend,
  both bitwise the chunked pool trajectory — CI drives the matmul tier
  bitwise-vs-chunked on every push (tests/test_delivery_matmul.py).

Usage: python scripts/hbm_sharded_smoke.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from cop5615_gossip_protocol_tpu.utils import compat

    jax.config.update("jax_threefry_partitionable", True)
    compat.set_host_device_count(2)

    from cop5615_gossip_protocol_tpu import SimConfig, build_topology
    from cop5615_gossip_protocol_tpu.models.runner import run
    from cop5615_gossip_protocol_tpu.parallel.fused_hbm_sharded import (
        run_stencil_hbm_sharded,
    )
    from cop5615_gossip_protocol_tpu.parallel.mesh import make_mesh

    n = 65536
    rounds = 24
    topo = build_topology("ring", n)
    grab = {}
    r1 = run(
        topo,
        SimConfig(n=n, topology="ring", algorithm="gossip",
                  engine="chunked", max_rounds=rounds, chunk_rounds=rounds),
        on_chunk=lambda r, s: grab.update(a=s),
    )
    cfg = SimConfig(n=n, topology="ring", algorithm="gossip",
                    engine="fused", n_devices=2, chunk_rounds=2,
                    max_rounds=rounds)
    r2 = run_stencil_hbm_sharded(
        topo, cfg, mesh=make_mesh(2), on_chunk=lambda r, s: grab.update(b=s)
    )
    assert r1.rounds == r2.rounds == rounds, (r1.rounds, r2.rounds)
    assert r1.converged_count == r2.converged_count
    for f in ("count", "active", "conv"):
        a = np.asarray(getattr(grab["a"], f))
        b = np.asarray(getattr(grab["b"], f))[:n]
        assert (a == b).all(), f"{f} diverged"
    print(f"[hbm-sharded-smoke] one-sweep fallback bitwise OK "
          f"({rounds} rounds, conv {r2.converged_count})")

    # DMA-transport trace: zero XLA collectives on the halo path.
    cfg_dma = SimConfig(n=n, topology="ring", algorithm="gossip",
                        engine="fused", n_devices=2, chunk_rounds=2,
                        max_rounds=rounds, halo_dma="on")
    probed = {}

    def probe(fn, args, **info):
        probed["txt"] = str(jax.make_jaxpr(fn)(*args))
        return None

    run_stencil_hbm_sharded(topo, cfg_dma, mesh=make_mesh(2), probe=probe)
    assert "ppermute" not in probed["txt"], "DMA path still carries ppermute"
    assert "dma_start" in probed["txt"]
    print("[hbm-sharded-smoke] in-kernel-dma trace OK (no ppermute)")

    # --- imp x HBM x sharded (ISSUE 10) --------------------------------
    from cop5615_gossip_protocol_tpu.parallel.fused_imp_hbm_sharded import (
        run_imp_hbm_sharded,
    )

    n_imp, rounds_imp = 27_000, 10
    topo_imp = build_topology("imp3d", n_imp)
    grab = {}
    r1 = run(
        topo_imp,
        SimConfig(n=n_imp, topology="imp3d", algorithm="gossip",
                  delivery="pool", engine="chunked",
                  max_rounds=rounds_imp, chunk_rounds=rounds_imp),
        on_chunk=lambda r, s: grab.update(a=s),
    )
    cfg_imp = SimConfig(n=n_imp, topology="imp3d", algorithm="gossip",
                        delivery="pool", engine="fused", n_devices=2,
                        chunk_rounds=1, max_rounds=rounds_imp)
    r2 = run_imp_hbm_sharded(
        topo_imp, cfg_imp, mesh=make_mesh(2),
        on_chunk=lambda r, s: grab.update(b=s),
    )
    assert r1.rounds == r2.rounds == rounds_imp, (r1.rounds, r2.rounds)
    for f in ("count", "active", "conv"):
        a = np.asarray(getattr(grab["a"], f))
        b = np.asarray(getattr(grab["b"], f))[:n_imp]
        assert (a == b).all(), f"imp {f} diverged"
    print(f"[hbm-sharded-smoke] imp3d x HBM x sharded bitwise OK "
          f"({rounds_imp} rounds, informed {int(np.asarray(grab['b'].count).astype(bool).sum())})")

    # imp DMA-transport trace: the lattice halo moves in-kernel, the
    # pooled long-range classes keep their ONE all_gather.
    probed.clear()
    run_imp_hbm_sharded(
        topo_imp,
        SimConfig(n=n_imp, topology="imp3d", algorithm="gossip",
                  delivery="pool", engine="fused", n_devices=2,
                  chunk_rounds=1, max_rounds=rounds_imp, halo_dma="on"),
        mesh=make_mesh(2), probe=probe,
    )
    assert "ppermute" not in probed["txt"], "imp DMA path carries ppermute"
    assert "dma_start" in probed["txt"]
    assert "all-gather" in probed["txt"] or "all_gather" in probed["txt"]
    print("[hbm-sharded-smoke] imp in-kernel-dma trace OK "
          "(no ppermute, pool all_gather kept)")

    # --- replicated-pool2 (ISSUE 10) -----------------------------------
    from cop5615_gossip_protocol_tpu.parallel.pool2_sharded import (
        run_pool2_sharded,
    )

    n_full, rounds_full = 262_144, 8
    topo_full = build_topology("full", n_full)
    grab = {}
    r1 = run(
        topo_full,
        SimConfig(n=n_full, topology="full", algorithm="gossip",
                  delivery="pool", engine="chunked",
                  max_rounds=rounds_full, chunk_rounds=rounds_full),
        on_chunk=lambda r, s: grab.update(a=s),
    )
    r2 = run_pool2_sharded(
        topo_full,
        SimConfig(n=n_full, topology="full", algorithm="gossip",
                  delivery="pool", engine="fused", n_devices=2,
                  chunk_rounds=1, max_rounds=rounds_full),
        mesh=make_mesh(2), on_chunk=lambda r, s: grab.update(b=s),
    )
    assert r1.rounds == r2.rounds == rounds_full, (r1.rounds, r2.rounds)
    for f in ("count", "active", "conv"):
        a = np.asarray(getattr(grab["a"], f))
        b = np.asarray(getattr(grab["b"], f))[:n_full]
        assert (a == b).all(), f"pool2 {f} diverged"
    print(f"[hbm-sharded-smoke] replicated-pool2 full bitwise OK "
          f"({rounds_full} rounds, informed {int(np.asarray(grab['b'].count).astype(bool).sum())})")

    # Banded reduce_scatter wire (ISSUE 15): each device receives only
    # the O(N/P + margins) summary bands its pool-slot windows consume
    # (segmented psum_scatters + one margin ppermute volley) instead of
    # the full gathered copy — forced at 2 devices (auto would pick the
    # gather wire on a mesh narrower than the pool) and bitwise the SAME
    # chunked oracle, executing the band path end-to-end on every push.
    r3 = run_pool2_sharded(
        topo_full,
        SimConfig(n=n_full, topology="full", algorithm="gossip",
                  delivery="pool", engine="fused", n_devices=2,
                  chunk_rounds=1, max_rounds=rounds_full,
                  pool2_wire="reduce_scatter"),
        mesh=make_mesh(2), on_chunk=lambda r, s: grab.update(c=s),
    )
    assert r1.rounds == r3.rounds == rounds_full, (r1.rounds, r3.rounds)
    for f in ("count", "active", "conv"):
        a = np.asarray(getattr(grab["a"], f))
        c = np.asarray(getattr(grab["c"], f))[:n_full]
        assert (a == c).all(), f"pool2 reduce_scatter-wire {f} diverged"
    print("[hbm-sharded-smoke] replicated-pool2 reduce_scatter wire "
          "bitwise OK")

    # --- MXU matmul tier (ISSUE 12) ------------------------------------
    # Same rounds, same stream: the pool2-sharded composition with the
    # per-shard one-hot MXU blend must be bitwise the chunked pool
    # trajectory captured above (gossip sums are integer-exact under any
    # summation order) — the blend swap moves compute units, never bits.
    r4 = run_pool2_sharded(
        topo_full,
        SimConfig(n=n_full, topology="full", algorithm="gossip",
                  delivery="matmul", engine="fused", n_devices=2,
                  chunk_rounds=1, max_rounds=rounds_full),
        mesh=make_mesh(2), on_chunk=lambda r, s: grab.update(d=s),
    )
    assert r1.rounds == r4.rounds == rounds_full, (r1.rounds, r4.rounds)
    for f in ("count", "active", "conv"):
        a = np.asarray(getattr(grab["a"], f))
        d = np.asarray(getattr(grab["d"], f))[:n_full]
        assert (a == d).all(), f"pool2-sharded matmul {f} diverged"
    print("[hbm-sharded-smoke] replicated-pool2 matmul blend bitwise OK")

    # Chunked one-hot dot_general round vs the chunked pool round, to
    # convergence, at a dense-tier-friendly size (the one-hot form does
    # O(n/128) MACs per delivered element — n^2-class work that only the
    # MXU makes free, so the CPU smoke stays small on purpose).
    n_mm = 4096
    topo_mm = build_topology("full", n_mm)
    grab_mm = {}
    runs = {}
    for d in ("pool", "matmul"):
        runs[d] = run(
            topo_mm,
            SimConfig(n=n_mm, topology="full", algorithm="gossip",
                      delivery=d, max_rounds=5000),
            on_chunk=lambda r, s, d=d: grab_mm.update({d: s}),
        )
    assert runs["pool"].rounds == runs["matmul"].rounds
    assert runs["pool"].converged and runs["matmul"].converged
    for f in ("count", "active", "conv"):
        a = np.asarray(getattr(grab_mm["pool"], f))
        b = np.asarray(getattr(grab_mm["matmul"], f))
        assert (a == b).all(), f"chunked matmul {f} diverged from pool"
    print(f"[hbm-sharded-smoke] MXU matmul tier bitwise OK "
          f"(chunked one-hot dot_general, n={n_mm}, "
          f"{runs['matmul'].rounds} rounds to convergence)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
